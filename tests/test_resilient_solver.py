"""Resilient solver execution: deadline, classification, breaker, invariant
gate, fallback routing — plus the fault-injected chaos runs that prove the
full operator loop survives a dying device (ISSUE 2 acceptance)."""

import dataclasses

import pytest

from karpenter_tpu import faults
from karpenter_tpu.api import wellknown as wk
from karpenter_tpu.api.objects import ObjectMeta, Pod
from karpenter_tpu.controllers import store as st
from karpenter_tpu.metrics.registry import (
    CONTROLLER_ERRORS,
    REPAIR_BREAKER_OPEN,
    SOLVER_BREAKER_STATE,
    SOLVER_FALLBACK,
)
from karpenter_tpu.operator.operator import new_kwok_operator
from karpenter_tpu.provisioning.scheduler import (
    ClaimResult,
    ExistingNode,
    SolverInput,
    SolverResult,
)
from karpenter_tpu.scheduling.requirements import Requirements
from karpenter_tpu.solver.backend import ReferenceSolver, Solver, TPUSolver
from karpenter_tpu.solver.encode import quantize_input
from karpenter_tpu.solver.resilient import (
    CircuitBreaker,
    InvariantViolation,
    ResilientSolver,
    SolveTimeout,
    check_invariants,
    classify_failure,
)
from karpenter_tpu.utils.resources import PODS, Resources

from tests.test_e2e_kwok import FakeClock, mkpool
from tests.test_solver_parity import ZONES, mkpod, pool


def _inp(pods, nodes=()):
    return SolverInput(pods=list(pods), nodes=list(nodes),
                       nodepools=[pool()], zones=ZONES)


# -- classification ----------------------------------------------------------


def test_classify_failure():
    assert classify_failure(SolveTimeout("late")) == "timeout"
    assert classify_failure(faults.DeviceError("xla died")) == "device_error"
    assert classify_failure(RuntimeError("RESOURCE_EXHAUSTED")) == "device_error"
    assert classify_failure(MemoryError()) == "device_error"
    assert classify_failure(OSError("tunnel")) == "device_error"
    assert classify_failure(ValueError("bad shape")) == "encode_bug"
    assert classify_failure(IndexError()) == "encode_bug"
    assert classify_failure(faults.DecodeError("garbage")) == "device_error"
    assert classify_failure(StopIteration()) == "unknown"


# -- circuit breaker ---------------------------------------------------------


def test_breaker_opens_probes_and_recovers():
    clock = FakeClock()
    b = CircuitBreaker(threshold=3, probe_interval_s=30.0, clock=clock)
    assert b.state == "closed" and b.allow()
    b.record_failure()
    b.record_failure()
    assert b.state == "closed"  # below threshold
    b.record_failure()
    assert b.state == "open"
    assert SOLVER_BREAKER_STATE.value() == 2.0
    assert not b.allow()  # interval not elapsed: straight to fallback
    clock.advance(29)
    assert not b.allow()
    clock.advance(2)
    assert b.allow()  # half-open: one probe
    assert b.state == "half-open"
    assert SOLVER_BREAKER_STATE.value() == 1.0
    assert not b.allow()  # concurrent solve while probing: fallback
    b.record_success()
    assert b.state == "closed" and b.allow()
    assert SOLVER_BREAKER_STATE.value() == 0.0


def test_breaker_probe_failure_reopens():
    clock = FakeClock()
    b = CircuitBreaker(threshold=1, probe_interval_s=10.0, clock=clock)
    b.record_failure()
    assert b.state == "open"
    clock.advance(11)
    assert b.allow()
    b.record_failure()  # probe failed
    assert b.state == "open"
    assert not b.allow()  # new interval started
    clock.advance(11)
    assert b.allow()


# -- invariant gate ----------------------------------------------------------


def _gate_fixture():
    pods = [mkpod("a", cpu="1"), mkpod("b", cpu="1")]
    node = ExistingNode(
        id="n1", labels={}, taints=[],
        free=Resources.parse({"cpu": "1", "memory": "4Gi", "pods": "10"}),
    )
    inp = _inp(pods, [node])
    return pods, node, quantize_input(inp)


def _claim(uids):
    return ClaimResult(nodepool="default", requirements=Requirements(),
                       instance_type_names=["m5.large"], pod_uids=list(uids),
                       requests=Resources.parse({"cpu": "1"}), taints=[],
                       hostname="h")


def test_gate_accepts_valid_result():
    _, _, q = _gate_fixture()
    res = SolverResult(
        placements={"a": ("node", "n1"), "b": ("claim", 0)},
        claims=[_claim(["b"])], errors={},
    )
    assert check_invariants(q, res) == []


def test_gate_rejects_phantom_node_and_bad_slot():
    _, _, q = _gate_fixture()
    res = SolverResult(placements={"a": ("node", "ghost"), "b": ("claim", 3)},
                       claims=[_claim([])], errors={})
    v = check_invariants(q, res)
    assert any("phantom node" in s for s in v)
    assert any("out-of-range claim slot" in s for s in v)


def test_gate_rejects_oversubscription():
    # both 1-cpu pods on a node with 1 cpu free
    _, _, q = _gate_fixture()
    res = SolverResult(
        placements={"a": ("node", "n1"), "b": ("node", "n1")},
        claims=[], errors={},
    )
    v = check_invariants(q, res)
    assert any("oversubscribed on cpu" in s for s in v)


def test_gate_rejects_pod_slot_oversubscription():
    pods = [mkpod(f"p{i}", cpu="1m", mem="1Mi") for i in range(3)]
    node = ExistingNode(
        id="n1", labels={}, taints=[],
        free=Resources.parse({"cpu": "10", "memory": "4Gi", "pods": "2"}),
    )
    q = quantize_input(_inp(pods, [node]))
    res = SolverResult(
        placements={p.meta.uid: ("node", "n1") for p in pods},
        claims=[], errors={},
    )
    v = check_invariants(q, res)
    assert any("pod slots oversubscribed" in s for s in v)


def test_gate_rejects_claim_uid_mismatch_and_overlap():
    _, _, q = _gate_fixture()
    res = SolverResult(
        placements={"a": ("claim", 0)},
        claims=[_claim(["a", "b"])],  # b never placed on slot 0
        errors={"a": "also errored"},  # overlaps placements
    )
    v = check_invariants(q, res)
    assert any("inconsistent with placements" in s for s in v)
    assert any("both placed and errored" in s for s in v)


# -- ResilientSolver routing -------------------------------------------------


class _ScriptedSolver(Solver):
    """Inner backend whose outcomes are scripted per solve: an exception
    instance (raised), a SolverResult (returned), or 'oracle' (delegate)."""

    def __init__(self, *outcomes, clock=None, advance=0.0):
        self.outcomes = list(outcomes)
        self.calls = 0
        self.clock = clock
        self.advance = advance  # FakeClock seconds consumed per solve

    def solve(self, inp):
        self.calls += 1
        if self.clock is not None and self.advance:
            self.clock.advance(self.advance)
        out = self.outcomes.pop(0) if self.outcomes else "oracle"
        if isinstance(out, BaseException):
            raise out
        if out == "oracle":
            return ReferenceSolver().solve(inp)
        return out


def test_device_error_falls_back_to_oracle():
    clock = FakeClock()
    inner = _ScriptedSolver(faults.DeviceError("xla"))
    rs = ResilientSolver(inner, fallbacks=[ReferenceSolver()], clock=clock)
    before = SOLVER_FALLBACK.value(reason="device_error")
    inp = _inp([mkpod("a")])
    res = rs.solve(inp)
    assert res.placements["a"][0] == "claim"
    assert SOLVER_FALLBACK.value(reason="device_error") == before + 1
    assert rs.resilient_stats["fallback"] == 1
    assert rs.breaker.consecutive_failures == 1


def test_posthoc_deadline_trips_and_falls_back():
    clock = FakeClock()
    inner = _ScriptedSolver("oracle", clock=clock, advance=10.0)
    rs = ResilientSolver(inner, fallbacks=[ReferenceSolver()],
                         deadline_s=5.0, clock=clock)
    assert rs.deadline_mode == "posthoc"  # auto: non-wall clock injected
    before = SOLVER_FALLBACK.value(reason="timeout")
    res = rs.solve(_inp([mkpod("a")]))
    assert res.placements["a"][0] == "claim"  # served by fallback
    assert SOLVER_FALLBACK.value(reason="timeout") == before + 1


def test_thread_deadline_abandons_hung_solve():
    import threading

    release = threading.Event()

    class Hung(Solver):
        def solve(self, inp):
            release.wait(5)
            return ReferenceSolver().solve(inp)

    rs = ResilientSolver(Hung(), fallbacks=[ReferenceSolver()],
                         deadline_s=0.05, deadline_mode="thread")
    before = SOLVER_FALLBACK.value(reason="timeout")
    res = rs.solve(_inp([mkpod("a")]))
    release.set()
    assert res.placements["a"][0] == "claim"
    assert SOLVER_FALLBACK.value(reason="timeout") == before + 1


def test_gate_rejection_replays_on_fallback():
    garbage = SolverResult(placements={"a": ("node", "ghost")}, claims=[],
                           errors={})
    inner = _ScriptedSolver(garbage)
    rs = ResilientSolver(inner, fallbacks=[ReferenceSolver()],
                         clock=FakeClock())
    before = SOLVER_FALLBACK.value(reason="invariant_gate")
    res = rs.solve(_inp([mkpod("a")]))
    assert res.placements["a"][0] == "claim"  # oracle's valid result
    assert rs.resilient_stats["gate_rejections"] == 1
    assert SOLVER_FALLBACK.value(reason="invariant_gate") == before + 1


def test_exhausted_chain_raises_invariant_violation():
    garbage = SolverResult(placements={"a": ("node", "ghost")}, claims=[],
                           errors={})
    inner = _ScriptedSolver(garbage)
    bad_fb = _ScriptedSolver(dataclasses.replace(garbage))
    rs = ResilientSolver(inner, fallbacks=[bad_fb], clock=FakeClock())
    with pytest.raises(InvariantViolation):
        rs.solve(_inp([mkpod("a")]))


def test_breaker_short_circuits_device_and_recovers_on_probe():
    clock = FakeClock()
    inner = _ScriptedSolver(
        faults.DeviceError("1"), faults.DeviceError("2"),  # trip (threshold 2)
    )
    rs = ResilientSolver(inner, fallbacks=[ReferenceSolver()],
                         breaker_threshold=2, breaker_probe_s=30.0,
                         clock=clock)
    inp = _inp([mkpod("a")])
    rs.solve(inp)
    rs.solve(inp)
    assert rs.breaker.state == "open"
    before_calls = inner.calls
    before_sc = SOLVER_FALLBACK.value(reason="breaker_open")
    res = rs.solve(inp)  # open: device never consulted
    assert inner.calls == before_calls
    assert res.placements and rs.resilient_stats["breaker_short_circuits"] == 1
    assert SOLVER_FALLBACK.value(reason="breaker_open") == before_sc + 1
    clock.advance(31)
    res = rs.solve(inp)  # half-open probe: inner now healthy again
    assert inner.calls == before_calls + 1
    assert rs.breaker.state == "closed"
    assert res.placements["a"][0] == "claim"


def test_delegates_attributes_to_inner():
    inner = TPUSolver()
    rs = ResilientSolver(inner, clock=FakeClock())
    assert rs.stats is inner.stats
    assert hasattr(rs, "warmup") and hasattr(rs, "prewarm_aot")
    assert not hasattr(ResilientSolver(ReferenceSolver(),
                                       clock=FakeClock()), "warmup")


# -- parity with the wrapper on both backends (acceptance) -------------------


def test_parity_holds_under_resilient_wrapper():
    from tests.test_solver_parity import assert_parity

    import random

    random.seed(7)
    pods = [
        mkpod(f"p{i:03d}", cpu=f"{random.choice([100, 250, 500, 1000])}m",
              mem=f"{random.choice([128, 256, 512, 1024])}Mi")
        for i in range(40)
    ]
    inp = _inp(pods)
    ref = ResilientSolver(ReferenceSolver(), clock=FakeClock()).solve(
        quantize_input(inp))
    tpu = ResilientSolver(TPUSolver(), clock=FakeClock()).solve(inp)
    # same exactness bar as assert_parity's core checks
    assert ref.placements == tpu.placements
    assert set(ref.errors) == set(tpu.errors)
    assert len(ref.claims) == len(tpu.claims)
    for rc, tc in zip(ref.claims, tpu.claims):
        assert rc.pod_uids == tc.pod_uids
        assert sorted(rc.instance_type_names) == sorted(tc.instance_type_names)
    # and the unwrapped oracle agrees: the wrapper was transparent
    bare = ReferenceSolver().solve(quantize_input(inp))
    assert bare.placements == ref.placements


# -- operator-loop chaos (acceptance: converge via fallback) -----------------


def _mkpods(op, n, prefix="c"):
    for i in range(n):
        op.store.create(st.PODS, Pod(
            meta=ObjectMeta(name=f"{prefix}{i:03d}", uid=f"{prefix}{i:03d}"),
            requests=Resources.parse({"cpu": "500m", "memory": "512Mi"}),
        ))


@pytest.mark.chaos
def test_operator_converges_while_device_dies_then_breaker_recovers():
    """solver.device_dispatch scripted to fail K times: every pod still
    binds (served by the fallback ladder), the breaker opens, and a later
    half-open probe against the recovered device closes it again."""
    clock = FakeClock()
    op = new_kwok_operator(
        clock=clock, solver=TPUSolver(),
        breaker_threshold=2, breaker_probe_s=30.0,
    )
    op.store.create(st.NODEPOOLS, mkpool())
    # the device is dead for the whole first phase (50 >> any dispatch count
    # the provisioner + disruption sims produce before the breaker opens)
    plan = faults.FaultPlan(seed=3)
    plan.fail_n("solver.device_dispatch", 50)
    before_dev = SOLVER_FALLBACK.value(reason="device_error")
    with faults.active(plan):
        _mkpods(op, 8, "k")
        for _ in range(6):
            op.manager.tick()
            clock.advance(1)
        op.manager.settle()
        assert op.solver.breaker.state == "open"
        assert all(p.node_name for p in op.store.list(st.PODS)), (
            "pods did not bind via fallback while the device was dead"
        )
        assert SOLVER_FALLBACK.value(reason="device_error") > before_dev
        assert plan.fired["solver.device_dispatch"] >= 2  # >= threshold
    # device recovered (fault scope exited); the next solve past the probe
    # interval is the half-open probe and closes the breaker
    clock.advance(31)
    _mkpods(op, 4, "r")
    for _ in range(6):
        op.manager.tick()
        clock.advance(1)
    op.manager.settle()
    assert op.solver.breaker.state == "closed"
    assert op.solver.stats["device_solves"] >= 1  # probe ran on-device
    assert all(p.node_name for p in op.store.list(st.PODS))


@pytest.mark.chaos
def test_gate_rejections_never_produce_a_nodeclaim():
    """A backend decoding garbage (placements onto a phantom node, claims
    with stray uids) must never materialize a NodeClaim from that garbage:
    the gate replays the solve on the oracle and only oracle claims land."""

    class GarbageFirst(Solver):
        def __init__(self):
            self.calls = 0

        def solve(self, inp):
            self.calls += 1
            if self.calls <= 2:
                uids = [p.meta.uid for p in inp.pods]
                return SolverResult(
                    placements={u: ("node", "phantom-node") for u in uids},
                    claims=[ClaimResult(
                        nodepool="default", requirements=Requirements(),
                        instance_type_names=["m5.large"],
                        pod_uids=["never-existed"],
                        requests=Resources.parse({"cpu": "1"}), taints=[],
                        hostname="x")],
                    errors={},
                )
            return ReferenceSolver().solve(inp)

    clock = FakeClock()
    inner = GarbageFirst()
    op = new_kwok_operator(clock=clock, solver=inner, breaker_threshold=99)
    op.store.create(st.NODEPOOLS, mkpool())
    _mkpods(op, 5, "g")
    for _ in range(6):
        op.manager.tick()
        clock.advance(1)
    op.manager.settle()
    assert inner.calls >= 1
    assert op.solver.resilient_stats["gate_rejections"] >= 1
    for c in op.store.list(st.NODECLAIMS):
        assert "never-existed" not in c.meta.name
    for p in op.store.list(st.PODS):
        assert p.node_name and p.node_name != "phantom-node"


@pytest.mark.chaos
def test_store_update_faults_are_contained_by_manager_backoff():
    """store.update dying under a controller must not kill the loop: the
    manager counts the error, backs the controller off, and the system
    converges once the fault clears."""
    clock = FakeClock()
    op = new_kwok_operator(clock=clock)
    op.store.create(st.NODEPOOLS, mkpool())
    plan = faults.FaultPlan(seed=1)
    plan.fail_n("store.update", 3, faults.FaultError("etcd burp"))
    with faults.active(plan):
        _mkpods(op, 4, "s")
        for _ in range(10):
            op.manager.tick()
            clock.advance(1)
    for _ in range(40):  # drain any backoff skips, then settle
        op.manager.tick()
        clock.advance(1)
    op.manager.settle()
    assert all(p.node_name for p in op.store.list(st.PODS))
    health = op.manager.health()
    assert all(h["consecutive_failures"] == 0 for h in health.values()), health


# -- manager containment -----------------------------------------------------


def test_manager_backoff_and_health():
    from karpenter_tpu.controllers.manager import Manager

    class Flaky:
        name = "flaky"

        def __init__(self):
            self.calls = 0
            self.fail = True

        def reconcile(self):
            self.calls += 1
            if self.fail:
                raise RuntimeError("boom")
            return False

    m = Manager()
    c = Flaky()
    m.register(c)
    before = CONTROLLER_ERRORS.value(controller="flaky")
    m.tick()  # fail #1 -> skip 1
    m.tick()  # skipped
    assert c.calls == 1
    assert m.health()["flaky"] == {
        "consecutive_failures": 1, "backoff_ticks_remaining": 0,
    }
    m.tick()  # fail #2 -> skip 2
    m.tick(); m.tick()  # skipped twice
    assert c.calls == 2
    assert CONTROLLER_ERRORS.value(controller="flaky") == before + 2
    c.fail = False
    m.tick()  # recovers
    assert c.calls == 3
    assert m.health()["flaky"]["consecutive_failures"] == 0
    m.tick()  # no backoff anymore
    assert c.calls == 4


def test_manager_backoff_is_capped():
    from karpenter_tpu.controllers.manager import BACKOFF_CAP, Manager

    class AlwaysFail:
        name = "af"

        def reconcile(self):
            raise RuntimeError("no")

    m = Manager()
    m.register(AlwaysFail())
    for _ in range(10):
        m.tick()
        m._skip["af"] = 0  # force retry each tick to drive the counter up
    assert m.health()["af"]["consecutive_failures"] == 10
    m.tick()
    assert m._skip["af"] <= BACKOFF_CAP


# -- satellites: launch throttling, token bucket, repair breaker -------------


def test_launch_throttle_is_per_claim(monkeypatch):
    """One throttled create must not abort the other launches this tick."""
    from karpenter_tpu.kwok.ratelimit import ThrottleError

    clock = FakeClock()
    op = new_kwok_operator(clock=clock)
    op.store.create(st.NODEPOOLS, mkpool())
    _mkpods(op, 1, "t")
    op.manager.settle()
    assert all(p.node_name for p in op.store.list(st.PODS))

    # now throttle exactly the FIRST create of the next wave
    from karpenter_tpu.lifecycle.controller import LaunchController

    launch = next(c for c in op.manager.controllers
                  if isinstance(c, LaunchController))
    real_create = op.cloud_provider.create
    state = {"throttled": 0}

    def flaky_create(claim, opts):
        if state["throttled"] < 1:
            state["throttled"] += 1
            raise ThrottleError("RequestLimitExceeded")
        return real_create(claim, opts)

    monkeypatch.setattr(op.cloud_provider, "create", flaky_create)
    # distinct-zone selectors -> one claim per pod, racing the same tick
    for i, z in enumerate(("zone-1a", "zone-1b", "zone-1c")):
        op.store.create(st.PODS, Pod(
            meta=ObjectMeta(name=f"big{i}", uid=f"big{i}"),
            requests=Resources.parse({"cpu": "7", "memory": "1Gi"}),
            node_selector={wk.ZONE_LABEL: z},
        ))
    for _ in range(3):
        op.manager.tick()
    launched = [c for c in op.store.list(st.NODECLAIMS) if c.launched]
    assert len(launched) >= 2, (
        "throttling one claim starved the rest of the batch"
    )
    clock.advance(2)  # past THROTTLE_BACKOFF_S: the throttled claim retries
    op.manager.settle()
    clock.advance(60)
    op.manager.settle()
    assert all(p.node_name for p in op.store.list(st.PODS))


def test_token_bucket_is_clock_injectable():
    from karpenter_tpu.kwok.ratelimit import TokenBucket

    clock = FakeClock()
    tb = TokenBucket(rate=1.0, burst=2, clock=clock)
    assert tb.try_take() and tb.try_take()
    assert not tb.try_take()  # burst drained, no wall sleep involved
    clock.advance(1)
    assert tb.try_take()  # refilled deterministically on the fake clock
    assert not tb.try_take()


def test_repair_breaker_gauge_sets_and_clears():
    from karpenter_tpu.cloudprovider.types import RepairPolicy
    from karpenter_tpu.lifecycle.repair import RepairController

    class FakeCP:
        def repair_policies(self):
            return [RepairPolicy(condition_type="Ready",
                                 condition_status="False",
                                 toleration_duration_s=30)]

    from karpenter_tpu.api.objects import Node

    clock = FakeClock()
    store = st.Store()
    rc = RepairController(store, FakeCP(), clock=clock)
    for i in range(4):
        store.create(st.NODES, Node(
            meta=ObjectMeta(name=f"n{i}"),
            allocatable=Resources.parse({"cpu": "4"}),
        ))
    # 3/4 unhealthy: breaker trips
    for i in range(3):
        n = store.get(st.NODES, f"n{i}")
        n.conditions["Ready"] = "False"
        n.condition_since["Ready"] = clock()
        store.update(st.NODES, n)
    rc.reconcile()
    assert REPAIR_BREAKER_OPEN.value() == 1.0
    # fleet heals to 1/6 unhealthy (<= 20%): breaker clears
    for i in range(1, 3):
        n = store.get(st.NODES, f"n{i}")
        n.conditions["Ready"] = "True"
        store.update(st.NODES, n)
    for i in range(4, 6):
        store.create(st.NODES, Node(
            meta=ObjectMeta(name=f"n{i}"),
            allocatable=Resources.parse({"cpu": "4"}),
        ))
    rc.reconcile()
    assert REPAIR_BREAKER_OPEN.value() == 0.0


# -- half-open probe vs concurrent submit through the pipeline (ISSUE 8) -----


def test_half_open_probe_races_concurrent_submit_through_pipeline():
    """The breaker's half-open admission happens at DISPATCH time on the
    pipeline's dispatcher thread. While the single probe solve is still in
    flight (held on a gate — no sleeps, FakeClock drives the schedule), a
    second request dispatched behind it must be short-circuited to the
    fallback, not admitted as a second probe; the probe's success then
    closes the breaker for traffic after both."""
    import threading

    from karpenter_tpu.solver.pipeline import DISRUPTION, SolveService

    probe_started = threading.Event()
    release_probe = threading.Event()

    class TripsThenGates(Solver):
        def __init__(self):
            self.calls = 0

        def solve(self, inp):
            self.calls += 1
            if self.calls <= 2:
                raise faults.DeviceError(f"dead {self.calls}")
            probe_started.set()
            assert release_probe.wait(10), "probe gate never released"
            return ReferenceSolver().solve(inp)

    clock = FakeClock()
    inner = TripsThenGates()
    rs = ResilientSolver(inner, fallbacks=[ReferenceSolver()],
                         breaker_threshold=2, breaker_probe_s=30.0,
                         clock=clock)
    svc = SolveService(rs, depth=2, clock=clock)
    inp = _inp([mkpod("a")])
    try:
        # trip: two device failures through the pipeline open the breaker
        for t in [svc.submit(inp, kind=DISRUPTION) for _ in range(2)]:
            t.result(timeout=30)
        assert rs.breaker.state == "open"
        assert inner.calls == 2
        clock.advance(31)  # probe interval elapsed: next allow() half-opens

        # spy on allow(): the concurrent submit's rejection is the race's
        # observable moment (it happens on the dispatcher thread)
        short_circuited = threading.Event()
        orig_allow = rs.breaker.allow

        def spy_allow():
            ok = orig_allow()
            if not ok:
                short_circuited.set()
            return ok

        rs.breaker.allow = spy_allow
        before_sc = SOLVER_FALLBACK.value(reason="breaker_open")
        t_probe = svc.submit(inp, kind=DISRUPTION)
        assert probe_started.wait(10), "half-open probe never dispatched"
        assert rs.breaker.state == "half-open"
        t_racer = svc.submit(inp, kind=DISRUPTION)  # races the open probe
        assert short_circuited.wait(10), "concurrent submit not rejected"
        release_probe.set()
        res_probe = t_probe.result(timeout=30)
        res_racer = t_racer.result(timeout=30)
        # exactly one probe reached the device; the racer was served by the
        # fallback; the successful probe closed the breaker
        assert inner.calls == 3
        assert rs.breaker.state == "closed"
        assert SOLVER_FALLBACK.value(reason="breaker_open") == before_sc + 1
        assert rs.resilient_stats["breaker_short_circuits"] == 1
        assert res_probe.placements["a"][0] == "claim"
        assert res_racer.placements["a"][0] == "claim"
    finally:
        release_probe.set()
        svc.close()


# -- deadline-leaked stray threads are tracked and reaped (ISSUE 8) ----------


def test_deadline_leaked_thread_gauge_tracks_and_reaps():
    """thread-mode deadline: a dispatch that outlives its deadline is
    abandoned but ACCOUNTED — the stray is tracked on the gauge until it
    finally dies, and a later healthy solve reaps it back to zero."""
    import threading

    from karpenter_tpu.metrics.registry import SOLVER_DEADLINE_LEAKED_THREADS

    release = threading.Event()

    class HangsOnce(Solver):
        def __init__(self):
            self.calls = 0

        def solve(self, inp):
            self.calls += 1
            if self.calls == 1:
                assert release.wait(10), "test never released the hung solve"
            return ReferenceSolver().solve(inp)

    rs = ResilientSolver(HangsOnce(), fallbacks=[ReferenceSolver()],
                         deadline_s=0.05, deadline_mode="thread")
    inp = _inp([mkpod("a")])
    res = rs.solve(inp)  # deadline trips; the dispatch thread is abandoned
    assert res.placements["a"][0] == "claim"  # fallback served it
    assert rs.leaked_threads == 1
    assert SOLVER_DEADLINE_LEAKED_THREADS.value() == 1.0
    stray = rs._strays[0]
    release.set()
    stray.join(timeout=10)
    assert not stray.is_alive()
    res2 = rs.solve(inp)  # healthy solve reaps the dead stray
    assert res2.placements["a"][0] == "claim"
    assert rs.leaked_threads == 0
    assert SOLVER_DEADLINE_LEAKED_THREADS.value() == 0.0
