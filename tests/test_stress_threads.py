"""Threaded stress: the race-discipline analog of the reference's `-race`
deflake loop (Makefile:70-77). The store is the shared-mutable heart of the
control plane (it IS the API server), so hammer it from many threads —
creators, updaters, deleters, a slow watcher, CAS contenders — and assert
the invariants the locking design promises: no exceptions, no lost objects,
watcher events delivered exactly once per mutation and never under a
stalled peer, CAS winners unique per round.
"""

import threading
import time

import pytest

from karpenter_tpu.api.objects import ObjectMeta, Pod
from karpenter_tpu.controllers import store as st
from karpenter_tpu.utils.resources import Resources


def mkpod(name):
    return Pod(meta=ObjectMeta(name=name, uid=name),
               requests=Resources.parse({"cpu": "100m", "memory": "64Mi"}))


class TestStoreUnderContention:
    N_THREADS = 8
    N_OPS = 300

    def test_create_update_delete_storm(self):
        store = st.Store()
        errors = []
        seen = []
        seen_lock = threading.Lock()

        def watcher(event, kind, obj):
            # deliberately slow-ish watcher: must not stall other mutators
            # (delivery happens outside the store lock)
            with seen_lock:
                seen.append((event, obj.meta.name, obj.meta.resource_version))

        store.watch(st.PODS, watcher)

        def worker(tid):
            try:
                for i in range(self.N_OPS):
                    name = f"t{tid}-p{i}"
                    store.create(st.PODS, mkpod(name))
                    p = store.get(st.PODS, name)
                    p.node_name = "n"
                    store.update(st.PODS, p)
                    if i % 3 == 0:
                        store.delete(st.PODS, name)
            except Exception as e:  # pragma: no cover
                errors.append((tid, repr(e)))

        threads = [threading.Thread(target=worker, args=(t,))
                   for t in range(self.N_THREADS)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errors, errors
        # every surviving pod is exactly the non-deleted set
        alive = {p.meta.name for p in store.list(st.PODS)}
        expect = {
            f"t{t}-p{i}"
            for t in range(self.N_THREADS)
            for i in range(self.N_OPS)
            if i % 3 != 0
        }
        assert alive == expect
        # drain any in-flight watcher deliveries, then check conservation:
        # one ADDED + one MODIFIED per pod, one DELETED per deleted pod
        deadline = time.monotonic() + 5
        want = self.N_THREADS * self.N_OPS
        while time.monotonic() < deadline:
            with seen_lock:
                n_added = sum(1 for e in seen if e[0] == "ADDED")
            if n_added >= want:
                break
            time.sleep(0.01)
        with seen_lock:
            kinds = {"ADDED": 0, "MODIFIED": 0, "DELETED": 0}
            per_pod_added = {}
            for event, name, rv in seen:
                kinds[event] += 1
                if event == "ADDED":
                    per_pod_added[name] = per_pod_added.get(name, 0) + 1
        assert kinds["ADDED"] == want
        assert kinds["MODIFIED"] == want
        assert kinds["DELETED"] == want // 3
        assert all(v == 1 for v in per_pod_added.values()), "duplicate ADDED"

    def test_cas_single_winner_per_round(self):
        """update_if under contention: exactly one winner per rv round."""
        from karpenter_tpu.controllers.leaderelection import Lease

        store = st.Store()
        store.create("leases", Lease(meta=ObjectMeta(name="l"), holder="nobody"))
        kind = "leases"
        wins = [0] * self.N_THREADS
        rounds = 60
        barrier = threading.Barrier(self.N_THREADS)

        def contender(tid):
            for r in range(rounds):
                barrier.wait()
                cur = store.get(kind, "l")
                barrier.wait()  # all contenders hold the SAME observed rv
                fresh = Lease(meta=ObjectMeta(name="l"), holder=f"t{tid}")
                try:
                    store.update_if(kind, fresh, cur.meta.resource_version)
                    wins[tid] += 1
                except st.Conflict:
                    pass
                barrier.wait()

        threads = [threading.Thread(target=contender, args=(t,))
                   for t in range(self.N_THREADS)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert sum(wins) == rounds, f"wins={wins} (must be exactly 1/round)"

    def test_watcher_deadlock_freedom(self):
        """A watcher that itself reads the store must not deadlock (delivery
        is outside the store lock), and a watcher wedged on a slow consumer
        must not block other threads' mutations."""
        store = st.Store()
        gate = threading.Event()
        read_back = []

        def reading_watcher(event, kind, obj):
            read_back.append(len(store.list(st.PODS)))  # re-enters the store
            if obj.meta.name == "slow":
                gate.wait(timeout=5)  # wedge this delivery

        store.watch(st.PODS, reading_watcher)
        store.create(st.PODS, mkpod("slow"))  # delivery wedges in this thread?

        # no: create() returns after enqueue; the drain happens on whichever
        # thread holds the dispatch lock. Prove OTHER mutators stay live
        # while the wedged delivery is in flight.
        done = threading.Event()

        def other():
            store.create(st.PODS, mkpod("fast"))
            done.set()

        t0 = threading.Thread(target=other)
        t1 = threading.Thread(target=lambda: store._drain())
        t1.start()
        t0.start()
        assert done.wait(timeout=3), "mutation stalled behind a slow watcher"
        gate.set()
        t0.join(timeout=5)
        t1.join(timeout=10)
        assert read_back, "watcher never saw its event"
