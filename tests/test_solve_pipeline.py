"""SolveService pipeline semantics: parity, coalescing, fairness, drain.

The pipelined solve service (solver/pipeline.py) owns the device seam; these
tests pin its contract: results are identical to a direct solve, a newer
provisioning snapshot supersedes every queued stale one (the stale ticket
raises Superseded and the stale input NEVER reaches the solver), the
dispatcher round-robins between provisioning and disruption classes, close()
fails queued work but drains in-flight work, and a dead device mid-pipeline
drains every in-flight request onto the resilient fallback ladder — none
lost, none double-executed (ISSUE 4 satellite: solver.device_dispatch chaos).
"""

import threading
from types import SimpleNamespace

import pytest

from karpenter_tpu import faults
from karpenter_tpu.provisioning.scheduler import SolverInput
from karpenter_tpu.solver.backend import ReferenceSolver, TPUSolver
from karpenter_tpu.solver.pipeline import (
    DISRUPTION,
    PROVISIONING,
    ServiceStopped,
    SolveService,
    Superseded,
)
from karpenter_tpu.solver.resilient import ResilientSolver

from tests.test_batched_consolidation import ZONES, mkpod, pool


def mkinput(pod_name="a", cpu="250m"):
    return SolverInput(
        pods=[mkpod(pod_name, cpu=cpu)], nodes=[], nodepools=[pool()], zones=ZONES
    )


class GatedAsyncSolver:
    """Async-seam stand-in whose DISPATCH blocks until `gate` is set, so a
    test controls exactly what sits in the service queue. Records dispatch
    order (provisioning inputs by pod name)."""

    def __init__(self):
        self.gate = threading.Event()
        self.dispatching = threading.Event()  # set once a dispatch has begun
        self.order = []
        self.solved = []

    def solve_async(self, inp):
        self.dispatching.set()
        assert self.gate.wait(10), "test gate never released"
        self.order.append(inp.pods[0].meta.name)
        self.solved.append(inp)
        return SimpleNamespace(result=lambda: ("ok", inp.pods[0].meta.name))


class SyncOnlySolver:
    """Backend without an async seam (the reference-oracle shape)."""

    def __init__(self):
        self.solved = []

    def solve(self, inp):
        self.solved.append(inp)
        return ("sync", inp.pods[0].meta.name)


# ---------------------------------------------------------------- mechanics


def test_parity_through_service():
    solver = ReferenceSolver()
    svc = SolveService(solver, depth=2)
    try:
        inp = mkinput("par")
        direct = solver.solve(mkinput("par"))
        via = svc.submit(inp, kind=PROVISIONING).result(timeout=30)
        assert via.errors == direct.errors
        assert via.placements == direct.placements
        assert len(via.claims) == len(direct.claims)
    finally:
        svc.close()


def test_sync_only_backend_degrades_to_fifo():
    solver = SyncOnlySolver()
    svc = SolveService(solver, depth=2)
    try:
        tickets = [svc.submit(mkinput(f"s{i}"), kind=DISRUPTION) for i in range(3)]
        assert [t.result(timeout=30) for t in tickets] == [
            ("sync", "s0"), ("sync", "s1"), ("sync", "s2")
        ]
        assert [inp.pods[0].meta.name for inp in solver.solved] == ["s0", "s1", "s2"]
    finally:
        svc.close()


def test_coalescing_supersedes_every_queued_provisioning_request():
    solver = GatedAsyncSolver()
    svc = SolveService(solver, depth=2)
    try:
        t1 = svc.submit(mkinput("p1"), kind=PROVISIONING, rev=("r", 1))
        assert solver.dispatching.wait(10)  # p1 popped: no longer coalescible
        t2 = svc.submit(mkinput("p2"), kind=PROVISIONING, rev=("r", 2))
        t3 = svc.submit(mkinput("p3"), kind=PROVISIONING, rev=("r", 3))
        # t2 is superseded IMMEDIATELY at t3's submit — no device involvement
        assert t2.done() and t2.superseded()
        with pytest.raises(Superseded) as ei:
            t2.result()
        assert ei.value.by is t3
        solver.gate.set()
        assert t1.result(timeout=30) == ("ok", "p1")
        assert t3.result(timeout=30) == ("ok", "p3")
        # the stale snapshot never reached the solver
        assert solver.order == ["p1", "p3"]
        assert svc.stats["coalesced"] == 1
        assert svc.stats["completed"] == 2
    finally:
        solver.gate.set()
        svc.close()


def test_fair_interleave_between_classes():
    solver = GatedAsyncSolver()
    svc = SolveService(solver, depth=1)
    try:
        t1 = svc.submit(mkinput("p1"), kind=PROVISIONING)
        assert solver.dispatching.wait(10)
        # queue one of each class while p1 blocks the dispatcher
        td = svc.submit_fn(
            lambda: (solver.order.append("d1"), (lambda: ("ok", "d1")))[1],
            kind=DISRUPTION,
        )
        t2 = svc.submit(mkinput("p2"), kind=PROVISIONING)
        assert svc.queue_depth() == 2
        solver.gate.set()
        for t in (t1, td, t2):
            t.result(timeout=30)
        # after a provisioning dispatch the disruption class gets the slot
        assert solver.order == ["p1", "d1", "p2"]
    finally:
        solver.gate.set()
        svc.close()


def test_submit_fn_resolves_with_finish_value():
    svc = SolveService(SyncOnlySolver(), depth=1)
    try:
        t = svc.submit_fn(lambda: (lambda: {"verdicts": [1, 2, 3]}), kind=DISRUPTION)
        assert t.result(timeout=30) == {"verdicts": [1, 2, 3]}
    finally:
        svc.close()


def test_close_fails_queued_and_drains_inflight():
    solver = GatedAsyncSolver()
    svc = SolveService(solver, depth=1)
    t1 = svc.submit(mkinput("p1"), kind=PROVISIONING)
    assert solver.dispatching.wait(10)
    t2 = svc.submit(mkinput("p2"), kind=PROVISIONING)
    closer = threading.Thread(target=svc.close)
    closer.start()
    # queued p2 fails fast even while p1 still holds the dispatcher
    with pytest.raises(ServiceStopped):
        t2.result(timeout=10)
    solver.gate.set()
    closer.join(timeout=30)
    assert not closer.is_alive()
    assert t1.result(timeout=10) == ("ok", "p1")  # in-flight work drained
    with pytest.raises(ServiceStopped):
        svc.submit(mkinput("p3"))
    assert 0.0 <= svc.occupancy() <= 1.0


def test_dispatch_error_delivers_to_caller():
    class Boom:
        def solve_async(self, inp):
            raise RuntimeError("encode exploded")

    svc = SolveService(Boom(), depth=2)
    try:
        t = svc.submit(mkinput("x"), kind=DISRUPTION)
        with pytest.raises(RuntimeError, match="encode exploded"):
            t.result(timeout=30)
        assert svc.stats["failed"] == 1
    finally:
        svc.close()


# ------------------------------------------------- chaos: dead device drain


def test_dead_device_mid_pipeline_drains_onto_fallback_ladder():
    """ISSUE 4 satellite: kill the device (solver.device_dispatch faults)
    while the pipeline holds multiple in-flight requests. Every request must
    resolve exactly once — the faulted ones via the fallback ladder, the
    rest on the recovered device — with no request lost or double-executed.
    """
    rs = ResilientSolver(TPUSolver(), fallbacks=[ReferenceSolver()])
    svc = SolveService(rs, depth=2)
    plan = faults.FaultPlan(seed=7).fail_n("solver.device_dispatch", 2)
    try:
        with faults.active(plan):
            inputs = [mkinput(f"c{i}", cpu="250m") for i in range(4)]
            tickets = [svc.submit(inp, kind=DISRUPTION) for inp in inputs]
            results = [t.result(timeout=120) for t in tickets]
        assert plan.fired["solver.device_dispatch"] == 2  # the fault fired
        for i, res in enumerate(results):
            assert not res.errors, f"request {i} unsolved: {res.errors}"
            assert len(res.claims) == 1
            assert res.claims[0].pod_uids == [f"c{i}"]
        # exactly once through the resilient layer per request: none lost,
        # none double-executed, faulted ones replayed on the fallback chain
        assert rs.resilient_stats["solves"] == 4
        assert rs.resilient_stats["fallback"] == 2
        assert svc.stats["completed"] == 4
        assert svc.stats["failed"] == 0
    finally:
        svc.close()


# ------------------------------------------- stop(): nothing blocks forever


def test_stop_resolves_every_ticket_even_with_wedged_dispatch():
    """ISSUE 8 satellite: stop() is the fleet's fencing primitive, so its
    contract is absolute — EVERY ticket the service ever issued resolves,
    even when the dispatcher is parked inside a dispatch that never returns
    (the gate is deliberately never released). Queued tickets fail fast with
    ServiceStopped; the wedged in-flight one is force-resolved after the
    drain window. No ticket.result() may block past its timeout."""
    solver = GatedAsyncSolver()
    svc = SolveService(solver, depth=1)
    t1 = svc.submit(mkinput("w1"), kind=DISRUPTION)
    assert solver.dispatching.wait(10)
    t2 = svc.submit(mkinput("w2"), kind=DISRUPTION)  # queued behind the wedge
    t3 = svc.submit(mkinput("w3"), kind=PROVISIONING)
    svc.stop(drain_s=0.1)  # wedge never releases: drain expires, force-resolve
    for t in (t1, t2, t3):
        with pytest.raises(ServiceStopped):
            t.result(timeout=5)
    assert svc.stats["failed"] >= 3
    with pytest.raises(ServiceStopped):
        svc.submit(mkinput("w4"))
    # the wedged dispatch eventually returns on the abandoned daemon thread;
    # its late delivery loses first-wins and must not flip the ticket
    err_before = t1.error()
    solver.gate.set()
    assert solver.gate.is_set()
    assert isinstance(t1.error(), ServiceStopped)
    assert t1.error() is err_before
