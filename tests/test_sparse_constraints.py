"""Sparse constraint engine (ISSUE 20): compacted V/Q-axis evaluation.

Four contracts, pinned here:

- the CSR wire layout of encode.sparse_run_tables — run-major [Sp, K] i32
  index tables, -1 padded, quantum-bucketed width, padding rows inert, and
  ladder rows the UNION over base + rung groups (any superset list is
  decision-identical because the kernel re-gathers membership through the
  index);
- the density gate (use_sparse_constraints) boundaries: combined width
  floor SPARSE_MIN_SIGS and the SPARSE_DENSITY_MAX fraction, both exact;
- randomized 3-leg parity: the sparse kernel leg must be DECISION-IDENTICAL
  to the dense leg and the host oracle across spread-only, affinity-only,
  and mixed fleets (mesh-sharded constrained parity lives in
  test_mesh_sharded_solve.py);
- the explain-flags memo keyed (id(group_pods), core_rev): a recycled id()
  from a collected encoding must never serve stale flags.
"""

import gc
import random
from types import SimpleNamespace

import numpy as np
import pytest

from karpenter_tpu.api import wellknown as wk
from karpenter_tpu.api.objects import PodAffinityTerm, TopologySpreadConstraint
from karpenter_tpu.provisioning.scheduler import SolverInput
from karpenter_tpu.solver import encode as enc_mod
from karpenter_tpu.solver.backend import ReferenceSolver, TPUSolver
from karpenter_tpu.solver.encode import (
    SPARSE_DENSITY_MAX,
    SPARSE_IDX_FLOOR,
    SPARSE_MIN_SIGS,
    constraint_density,
    encode,
    quantize_input,
    sparse_run_tables,
    use_sparse_constraints,
)

from tests.test_zone_device import ZONES, mknode, mkpod, pool


def _fake_enc(rg, Q=0, V=0, q_act=None, v_act=None):
    """Minimal enc stand-in for the pure-numpy table builders: member
    carries the activity, owner stays empty (the builders OR them)."""
    G = int(np.asarray(rg).max(initial=-1)) + 1
    zq = np.zeros((G, Q), bool)
    zv = np.zeros((G, V), bool)
    return SimpleNamespace(
        Q=Q, V=V, run_group=np.asarray(rg, np.int32),
        q_member=zq if q_act is None else np.asarray(q_act, bool),
        q_owner=np.zeros_like(zq if q_act is None else np.asarray(q_act)),
        v_member=zv if v_act is None else np.asarray(v_act, bool),
        v_owner=np.zeros_like(zv if v_act is None else np.asarray(v_act)),
    )


class TestSparseTableLayout:
    def test_csr_rows_list_active_sigs_in_order(self):
        q_act = np.zeros((3, 10), bool)
        q_act[0, [1, 9]] = True          # 2 actives
        q_act[2, :9] = True              # 9 actives -> width buckets to 16
        enc = _fake_enc([0, 1, 2, 0], Q=10, q_act=q_act)
        rqi, rvi = sparse_run_tables(enc, Sp=8)
        assert rqi.shape == (8, 16) and rqi.dtype == np.int32
        assert rqi[0, :2].tolist() == [1, 9] and (rqi[0, 2:] == -1).all()
        assert (rqi[1] == -1).all()      # inactive group: inert row
        assert rqi[2, :9].tolist() == list(range(9))
        assert (rqi[3] == rqi[0]).all()  # same group, same row
        assert (rqi[4:] == -1).all()     # Sp padding rows are inert
        # V axis absent: floor-width all-(-1) placeholder, never gathered
        assert rvi.shape == (8, SPARSE_IDX_FLOOR) and (rvi == -1).all()

    def test_owner_only_sigs_are_listed(self):
        """Ownership without membership (anti-affinity owners) must appear
        in the index list — the kernel needs the column to scatter owner
        state even when the group never counts as a member."""
        enc = _fake_enc([0], V=9)
        enc.v_owner[0, 7] = True
        rqi, rvi = sparse_run_tables(enc, Sp=1)
        assert rvi[0, 0] == 7 and (rvi[0, 1:] == -1).all()

    def test_ladder_rows_union_base_and_rung_groups(self):
        q_act = np.zeros((4, 12), bool)
        q_act[0, 2] = True               # base group of run 0
        q_act[1, 5] = True               # rung group
        q_act[2, 11] = True              # second rung group
        enc = _fake_enc([0, 3], Q=12, q_act=q_act)
        lad = np.array([[1, 2], [-1, -1]], np.int32)
        rqi, _ = sparse_run_tables(enc, Sp=2, run_ladder=lad)
        assert rqi[0, :3].tolist() == [2, 5, 11], (
            "ladder row must union base + every materialized rung group"
        )
        assert (rqi[1] == -1).all()      # -1 rungs contribute nothing


class TestDensityGate:
    def test_below_min_sigs_stays_dense(self):
        enc = _fake_enc(np.arange(8), Q=7)  # zero density, but too narrow
        assert constraint_density(enc) == 0.0
        assert use_sparse_constraints(enc) is False

    def test_density_boundary_is_exact(self):
        # S=8 runs x (Q+V)=8 sigs: 16 active pairs sit exactly ON the gate
        q_act = np.zeros((8, 8), bool)
        q_act.reshape(-1)[:16] = True
        enc = _fake_enc(np.arange(8), Q=8, q_act=q_act.copy())
        assert constraint_density(enc) == pytest.approx(SPARSE_DENSITY_MAX)
        assert use_sparse_constraints(enc) is True
        q_act.reshape(-1)[16] = True     # one pair above: dense wins
        enc2 = _fake_enc(np.arange(8), Q=8, q_act=q_act)
        assert use_sparse_constraints(enc2) is False

    def test_gate_on_real_constrained_fleet(self):
        tsc = TopologySpreadConstraint(
            max_skew=1, topology_key=wk.ZONE_LABEL,
            label_selector={"app": "w"},
        )
        pods = [mkpod(f"g{i}", labels={"app": "w"}, topology_spread=[tsc])
                for i in range(4)]
        pods += [mkpod(f"f{i:02d}", cpu=f"{1 + i % 4}") for i in range(30)]
        pods += [
            mkpod(f"v{i}", labels={"app": f"solo{i}"}, affinity_terms=[
                PodAffinityTerm(label_selector={"app": f"solo{i}"},
                                topology_key=wk.ZONE_LABEL, anti=True)])
            for i in range(7)
        ]
        enc = encode(quantize_input(SolverInput(
            pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)))
        assert enc.Q + enc.V >= SPARSE_MIN_SIGS
        assert 0.0 < constraint_density(enc) <= SPARSE_DENSITY_MAX
        assert use_sparse_constraints(enc) is True


# -- randomized 3-leg parity --------------------------------------------------


def _assert_same(a, b, tag):
    assert a.placements == b.placements, f"{tag}: placements diverge"
    assert set(a.errors) == set(b.errors), f"{tag}: errors diverge"
    assert len(a.claims) == len(b.claims), f"{tag}: claim count diverges"
    for i, (ca, cb) in enumerate(zip(a.claims, b.claims)):
        assert ca.pod_uids == cb.pod_uids, f"{tag}: claim {i} pods"
        assert sorted(ca.instance_type_names) == sorted(
            cb.instance_type_names
        ), f"{tag}: claim {i} types"


def _spread_fleet(rng, n_apps):
    pods = []
    for a in range(n_apps):
        tsc = TopologySpreadConstraint(
            max_skew=1, topology_key=wk.ZONE_LABEL,
            label_selector={"app": f"s{a}"},
        )
        for j in range(rng.randint(3, 5)):
            pods.append(mkpod(
                f"s{a}-{j}", cpu=rng.choice(["1", "2"]), mem="2Gi",
                labels={"app": f"s{a}"}, topology_spread=[tsc]))
    return pods


def _affinity_fleet(rng, n):
    pods = []
    for i in range(n):
        anti = PodAffinityTerm(label_selector={"app": f"a{i}"},
                               topology_key=wk.ZONE_LABEL, anti=True)
        pods.append(mkpod(f"a{i}", cpu="1", mem="1Gi",
                          labels={"app": f"a{i}"}, affinity_terms=[anti]))
    return pods


def _filler(rng, n):
    return [mkpod(f"p{i:03d}", cpu=rng.choice(["500m", "1", "2", "3"]),
                  mem=rng.choice(["1Gi", "2Gi", "4Gi"])) for i in range(n)]


class TestThreeLegParity:
    """Host oracle vs dense kernel vs sparse kernel: all three legs must
    decide identically — the sparse tables are an indexing of the SAME
    constraint state, never a relaxation."""

    def _run(self, pods, nodes, tag):
        inp = SolverInput(pods=pods, nodes=nodes, nodepools=[pool()],
                          zones=ZONES)
        host = ReferenceSolver().solve(inp)
        dense = TPUSolver(sparse="off")
        sparse = TPUSolver(sparse="on")
        _assert_same(dense.solve(inp), host, f"{tag}: dense-vs-host")
        _assert_same(sparse.solve(inp), host, f"{tag}: sparse-vs-host")
        assert dense.stats["sparse_dispatches"] == 0, dense.stats
        assert sparse.stats["sparse_dispatches"] == 1, sparse.stats

    def test_spread_fleet_parity(self):
        rng = random.Random(20)
        self._run(_spread_fleet(rng, 6) + _filler(rng, 12), [], "spread")

    def test_affinity_fleet_parity(self):
        rng = random.Random(21)
        self._run(_affinity_fleet(rng, 8) + _filler(rng, 12), [], "affinity")

    def test_mixed_fleet_parity_with_existing_nodes(self):
        rng = random.Random(22)
        pods = (_spread_fleet(rng, 5) + _affinity_fleet(rng, 6)
                + _filler(rng, 16))
        nodes = [mknode(f"n{i}", ZONES[i % 3]) for i in range(5)]
        self._run(pods, nodes, "mixed")

    def test_auto_gate_skips_tiny_constraint_axes(self):
        """auto on a fleet under the width floor must take the dense path
        (no sparse dispatch) and still decide with the oracle."""
        rng = random.Random(23)
        pods = _spread_fleet(rng, 2) + _filler(rng, 10)
        inp = SolverInput(pods=pods, nodes=[], nodepools=[pool()],
                          zones=ZONES)
        s = TPUSolver(sparse="auto")
        _assert_same(s.solve(inp), ReferenceSolver().solve(inp), "auto-tiny")
        assert s.stats["sparse_dispatches"] == 0, s.stats

    def test_sparse_knob_validates(self):
        with pytest.raises(ValueError):
            TPUSolver(sparse="sometimes")


# -- explain-flags memo: id() reuse guard -------------------------------------


def test_explain_flags_cache_id_reuse():
    """The memo key is (id(group_pods), core_rev). A collected encoding's
    id() can be recycled by a NEW group_pods list at the same address — if
    the key were id alone, the new encoding would inherit the old flags.
    Pin the guard two ways: a planted same-id/stale-rev entry must MISS,
    and a collect/re-allocate loop must always observe fresh flags."""
    from karpenter_tpu.solver.encode import _EXPLAIN_FLAGS_CACHE, explain_tables

    tsc = TopologySpreadConstraint(max_skew=1, topology_key=wk.ZONE_LABEL,
                                   label_selector={"app": "w"})

    def build(spread):
        kw = {"topology_spread": [tsc], "labels": {"app": "w"}} if spread else {}
        pods = [mkpod(f"e{i}", **kw) for i in range(3)]
        return encode(quantize_input(SolverInput(
            pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)))

    # 1. planted stale entry: same id(group_pods), predecessor core_rev,
    #    flags that are obviously wrong — the rev in the key must force a
    #    fresh compute instead of serving the plant
    enc = build(spread=False)
    G = int(enc.group_req.shape[0])
    _EXPLAIN_FLAGS_CACHE.clear()
    plant = (np.ones(G, bool), np.ones(G, bool))
    _EXPLAIN_FLAGS_CACHE[(id(enc.group_pods), enc.core_rev - 1)] = plant
    t = explain_tables(enc)
    assert not t["group_topo"].any() and not t["group_aff"].any(), (
        "stale same-id cache entry served across a core_rev change"
    )
    # the fresh compute is now memoized under the TRUE key: warm hit
    assert explain_tables(enc)["group_topo"] is t["group_topo"]

    # 2. hand-built encs (core_rev < 0) never populate the memo
    import dataclasses

    n_before = len(_EXPLAIN_FLAGS_CACHE)
    explain_tables(dataclasses.replace(enc, core_rev=-1))
    assert len(_EXPLAIN_FLAGS_CACHE) == n_before

    # 3. collect/re-allocate churn: alternate fleets with and without
    #    spread so any id-recycled hit would flip the flags visibly
    for i in range(6):
        spread = bool(i % 2)
        e = build(spread)
        flags = explain_tables(e)
        assert bool(flags["group_topo"].any()) == spread, (
            f"iteration {i}: recycled-id cache hit served stale flags"
        )
        del e, flags
        gc.collect()
