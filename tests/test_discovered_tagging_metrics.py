"""Discovered-capacity learning, post-registration tagging, per-offering
gauges, and the CloudProvider metrics decorator (VERDICT r3 missing #8 +
COMPONENTS partial rows: tagging, metrics gauge fill, metrics decorator).
"""

import pytest

from karpenter_tpu.api import wellknown as wk
from karpenter_tpu.controllers import store as st
from karpenter_tpu.controllers.tagging import TAGGED_ANNOTATION
from karpenter_tpu.metrics.registry import REGISTRY
from karpenter_tpu.operator.operator import new_kwok_operator
from karpenter_tpu.utils.resources import MEMORY

from tests.test_e2e_kwok import FakeClock, mkpod, mkpool


@pytest.fixture
def op():
    clock = FakeClock()
    o = new_kwok_operator(clock=clock)
    o.clock = clock
    return o


def provision_one(op):
    op.store.create(st.NODEPOOLS, mkpool())
    op.store.create(st.PODS, mkpod("p0", cpu="500m"))
    op.manager.settle()
    return op.store.list(st.NODES)[0], op.store.list(st.NODECLAIMS)[0]


class TestDiscoveredCapacity:
    def test_observed_memory_replaces_estimate(self, op):
        node, claim = provision_one(op)
        it_name = node.meta.labels[wk.INSTANCE_TYPE_LABEL]
        catalog_mem = next(
            it.capacity.get(MEMORY)
            for it in op.cloud_provider.get_instance_types("")
            if it.name == it_name
        )
        # the node reports LESS memory than the catalog estimated (real
        # hypervisor overhead): the served catalog must learn it
        observed = int(catalog_mem - 512 * 1024**2)
        node.capacity[MEMORY] = observed
        op.store.update(st.NODES, node)
        op.manager.settle()
        served = next(
            it for it in op.cloud_provider.get_instance_types("") if it.name == it_name
        )
        assert served.capacity.get(MEMORY) == observed

    def test_learning_bumps_catalog_seq(self, op):
        node, _ = provision_one(op)
        before = id(op.cloud_provider.get_instance_types(""))
        node.capacity[MEMORY] = int(node.capacity.get(MEMORY)) - 1024**2
        op.store.update(st.NODES, node)
        op.manager.settle()
        after = op.cloud_provider.get_instance_types("")
        assert id(after) != before, "catalog cache must rebuild on learning"


class TestTagging:
    def test_post_registration_tags(self, op):
        node, claim = provision_one(op)
        instance_id = claim.provider_id.rsplit("/", 1)[-1]
        inst = next(i for i in op.cloud.describe_instances() if i.id == instance_id)
        assert inst.tags.get("karpenter.sh/nodeclaim") == claim.name
        assert inst.tags.get("Name") == claim.node_name
        assert inst.tags.get(wk.NODEPOOL_LABEL) == claim.nodepool
        refreshed = op.store.get(st.NODECLAIMS, claim.name)
        assert refreshed.meta.annotations.get(TAGGED_ANNOTATION) == "true"


class TestMetrics:
    def test_offering_gauges_filled(self, op):
        provision_one(op)
        text = REGISTRY.expose()
        assert "karpenter_cloudprovider_instance_type_offering_available" in text
        assert "karpenter_cloudprovider_instance_type_offering_price_estimate" in text

    def test_cloudprovider_calls_metered(self, op):
        provision_one(op)
        text = REGISTRY.expose()
        assert 'karpenter_cloudprovider_duration_seconds' in text
        assert 'method="create"' in text or "method=\"get_instance_types\"" in text


class TestDiscoveredStability:
    def test_disagreeing_nodes_do_not_flip_flop(self):
        """Two live nodes of one type reporting different memory must not
        alternate the learned value (each flip bumps seq, and every seq bump
        rebuilds the served ~600-type catalog): the cache keeps the
        deterministic minimum and seq moves only on a new low."""
        from karpenter_tpu.providers.discovered import DiscoveredCapacityCache

        c = DiscoveredCapacityCache()
        for _ in range(5):  # reconcile loop listing both nodes, any order
            c.record("t3.large", 100)
            c.record("t3.large", 90)
        assert c.memory("t3.large") == 90
        assert c.seq == 2, "one bump per new minimum, not one per reconcile"
        c.record("t3.large", 95)  # higher observation: no churn
        assert c.memory("t3.large") == 90 and c.seq == 2


class TestSolverAndLeaderSeries:
    def test_solver_backend_counter_and_leader_gauge(self):
        from karpenter_tpu.controllers import store as st2
        from karpenter_tpu.controllers.leaderelection import LeaderElector
        from karpenter_tpu.metrics.registry import LEADER, REGISTRY, SOLVER_SOLVES
        from karpenter_tpu.solver.backend import TPUSolver
        from karpenter_tpu.provisioning.scheduler import SolverInput

        before = SOLVER_SOLVES.value(backend="device")
        from tests.test_zone_device import ZONES, mkpod, pool

        TPUSolver().solve(
            SolverInput(pods=[mkpod("m0")], nodes=[], nodepools=[pool()],
                        zones=ZONES)
        )
        assert SOLVER_SOLVES.value(backend="device") == before + 1
        s = st2.Store()
        el = LeaderElector(s, "me")
        el.tick()
        assert LEADER.value(identity="me") == 1.0
        # a co-hosted standby must not overwrite the leader's series
        el2 = LeaderElector(s, "standby")
        el2.tick()
        assert LEADER.value(identity="me") == 1.0
        assert LEADER.value(identity="standby") == 0.0
        el.resign()  # drops the gauge immediately (a lone elector would
        # legitimately re-win the freed lease on its next tick)
        assert LEADER.value(identity="me") == 0.0
        text = REGISTRY.expose()
        assert "karpenter_tpu_solver_solves_total" in text
        assert "karpenter_leader" in text
