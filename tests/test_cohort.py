"""Cross-tenant fused cohort dispatch (SPEC.md "Cohort semantics").

The mux extends each WFQ winner into a same-quantum-bucket cohort that the
backend serves with ONE kernel launch; these tests pin the contract: the
grouped dispatch sequence is EXACTLY the legacy WFQ schedule (2:1 shares
still converge, per-tenant FIFO holds, knob off is byte-identical to the
single-head path); a poisoned cohort member charges only ITS tenant's
breaker and replays on ITS oracle while co-members keep their fused
results; quantum-bucket mismatches never fuse; the fused backend path
decides bit-identically to solo dispatch (placements, claims, explain
fingerprint, per-tenant metered bytes) across cohort sizes {1,2,4,8}; and
padding the batch to its bucket moves zero extra host->device bytes.
"""

import dataclasses
import random
import time

import jax
import numpy as np
import pytest

from karpenter_tpu.metrics.registry import (
    SOLVER_COHORT_POISON_REPLAYS,
    SOLVER_FUSED_DISPATCHES,
    TENANT_METER_H2D_BYTES,
)
from karpenter_tpu.obs import explain as obsexplain
from karpenter_tpu.parallel.sharded import pad_batch
from karpenter_tpu.provisioning.scheduler import SolverInput, SolverResult
from karpenter_tpu.solver.backend import TPUSolver
from karpenter_tpu.solver.pipeline import DISRUPTION, SolveTicket
from karpenter_tpu.solver.tenancy import TenantMux, quantum_bucket

from tests.test_batched_consolidation import ZONES, mkpod, pool
from tests.test_tenancy import FakeService, mkinput, mkregistry


class FakeCohortService(FakeService):
    """FakeService plus the cohort seam: submit_cohort records each fused
    dispatch as a tuple of (tenant_id, pod_name) and delivers every member
    exactly like submit would — honoring the gate and fail_marker per
    member, so poison lands on one ticket while co-members succeed."""

    def __init__(self, **kw):
        super().__init__(**kw)
        self.cohorts = []  # one tuple of (tenant_id, pod_name) per dispatch

    def submit_cohort(self, members):
        assert self.gate.wait(10)
        self.cohorts.append(tuple(
            (m["tenant_id"], m["inp"].pods[0].meta.name) for m in members
        ))
        tickets = []
        for m in members:
            t = SolveTicket(m["kind"], rev=m.get("rev"),
                            tenant_id=m["tenant_id"])
            name = m["inp"].pods[0].meta.name
            self.order.append((m["tenant_id"], name))
            self.stats["submitted"] += 1
            if self.fail_marker is not None and self.fail_marker in name:
                t._deliver(error=RuntimeError(f"poisoned input {name}"))
            else:
                t._deliver(result=("solved", m["tenant_id"], name))
            tickets.append(t)
        return tickets


# ------------------------------------------------------------ cohort picking


def test_cohort_forms_across_tenants_with_fifo_preserved():
    """Four equally-backlogged tenants, one downstream slot: the WFQ
    prefix rule fuses one head from EACH tenant per round (the fifth
    winner repeats a tenant and ends the scan), per-tenant FIFO survives
    the grouping, and every ticket resolves."""
    svc = FakeCohortService(size=1, depth=1, gated=True)
    mux = TenantMux(svc, mkregistry(("a", 1.0), ("b", 1.0),
                                    ("c", 1.0), ("d", 1.0)),
                    own_service=True)
    fused0 = SOLVER_FUSED_DISPATCHES.value()
    try:
        tickets = [mux.submit(mkinput("a-primer"), tenant_id="a",
                              kind=DISRUPTION)]
        time.sleep(0.05)  # primer holds the slot while the backlog builds
        for i in range(6):
            for t in "abcd":
                tickets.append(mux.submit(mkinput(f"{t}-{i}"), tenant_id=t,
                                          kind=DISRUPTION))
        svc.gate.set()
        for t in tickets:
            assert t.result(timeout=10)
        assert len(svc.cohorts) == 6
        for co in svc.cohorts:
            tids = [tid for tid, _ in co]
            assert len(co) == 4 and len(set(tids)) == 4, svc.cohorts
        for t in "abcd":
            seq = [n for tid, n in svc.order if tid == t and "primer" not in n]
            assert seq == [f"{t}-{i}" for i in range(6)]
        assert mux.unresolved() == 0
        assert mux.mux_stats["cohort_dispatches"] == 6
        assert mux.mux_stats["cohort_members"] == 24
        assert SOLVER_FUSED_DISPATCHES.value() == fused0 + 6
    finally:
        mux.close()


def test_wfq_shares_converge_with_cohorting_on():
    """The fused schedule is the legacy schedule, just grouped: with 2:1
    weights the flattened forward order still carries 2 a's and 1 b in
    every window, per-tenant FIFO holds, and fusing genuinely happened."""
    svc = FakeCohortService(size=1, depth=1, gated=True)
    mux = TenantMux(svc, mkregistry(("a", 2.0), ("b", 1.0)),
                    own_service=True)
    try:
        tickets = [mux.submit(mkinput("a-primer"), tenant_id="a",
                              kind=DISRUPTION)]
        time.sleep(0.05)
        for i in range(24):
            tickets.append(mux.submit(mkinput(f"a-{i}"), tenant_id="a",
                                      kind=DISRUPTION))
        for i in range(12):
            tickets.append(mux.submit(mkinput(f"b-{i}"), tenant_id="b",
                                      kind=DISRUPTION))
        svc.gate.set()
        for t in tickets:
            assert t.result(timeout=10)
        order = [tid for tid, _ in svc.order][1:]  # drop the primer
        assert len(order) == 36
        for k in range(1, 13):
            prefix = order[: 3 * k]
            assert abs(prefix.count("a") - 2 * k) <= 1, (k, order)
            assert abs(prefix.count("b") - k) <= 1, (k, order)
        a_seq = [n for tid, n in svc.order if tid == "a" and "primer" not in n]
        assert a_seq == [f"a-{i}" for i in range(24)]
        b_seq = [n for tid, n in svc.order if tid == "b"]
        assert b_seq == [f"b-{i}" for i in range(12)]
        # the a,a,b,... interleave fuses the (a,b) adjacencies
        assert any(len(c) == 2 for c in svc.cohorts)
        assert mux.unresolved() == 0
    finally:
        mux.close()


def test_single_tenant_cohort_of_one_rides_legacy_path():
    """A lone backlogged tenant never fuses with itself: every dispatch is
    the legacy single-head submit, in FIFO order, and the cohort seam is
    never touched."""
    svc = FakeCohortService(size=1, depth=1, gated=True)
    mux = TenantMux(svc, mkregistry(("a", 1.0)), own_service=True)
    try:
        tickets = [mux.submit(mkinput("a-primer"), tenant_id="a",
                              kind=DISRUPTION)]
        time.sleep(0.05)
        for i in range(8):
            tickets.append(mux.submit(mkinput(f"a-{i}"), tenant_id="a",
                                      kind=DISRUPTION))
        svc.gate.set()
        for t in tickets:
            assert t.result(timeout=10)
        assert svc.cohorts == []
        assert mux.mux_stats["cohort_dispatches"] == 0
        seq = [n for _, n in svc.order if "primer" not in n]
        assert seq == [f"a-{i}" for i in range(8)]
        assert mux.unresolved() == 0
    finally:
        mux.close()


def test_cohort_knob_off_is_byte_identical_legacy():
    """--solver-cohort=false must reproduce the legacy single-head path
    exactly: the identical submission sequence yields the identical
    forward order and results, with the cohort seam never called — while
    the knob-on run over the same sequence does fuse."""

    def run(cohort):
        svc = FakeCohortService(size=1, depth=1, gated=True)
        mux = TenantMux(svc, mkregistry(("a", 2.0), ("b", 1.0), ("c", 1.0)),
                        own_service=True, cohort=cohort)
        try:
            tickets = [mux.submit(mkinput("a-primer"), tenant_id="a",
                                  kind=DISRUPTION)]
            time.sleep(0.05)
            for i in range(8):
                for t in "abc":
                    tickets.append(mux.submit(mkinput(f"{t}-{i}"),
                                              tenant_id=t, kind=DISRUPTION))
            svc.gate.set()
            results = [t.result(timeout=10) for t in tickets]
            assert mux.unresolved() == 0
            return svc.order, svc.cohorts, results
        finally:
            mux.close()

    order_on, cohorts_on, res_on = run(True)
    order_off, cohorts_off, res_off = run(False)
    assert cohorts_off == []  # seam untouched with the knob off
    assert cohorts_on  # ... and genuinely exercised with it on
    assert order_off == order_on  # same schedule, just grouped
    assert res_off == res_on


def test_quantum_bucket_mismatch_never_fuses():
    """Heads from different quantum buckets cannot share a fused launch:
    interleaved small/large backlogs dispatch solo, losslessly."""
    assert quantum_bucket(mkinput("x")) == quantum_bucket(
        mkinput("y", cpu="250m"))
    big = SolverInput(pods=[mkpod(f"big-{j}") for j in range(20)],
                      nodes=[], nodepools=[pool()], zones=ZONES)
    assert quantum_bucket(big) != quantum_bucket(mkinput("x"))
    svc = FakeCohortService(size=1, depth=1, gated=True)
    mux = TenantMux(svc, mkregistry(("a", 1.0), ("b", 1.0)),
                    own_service=True)
    try:
        tickets = [mux.submit(mkinput("a-primer"), tenant_id="a",
                              kind=DISRUPTION)]
        time.sleep(0.05)
        for i in range(3):
            tickets.append(mux.submit(mkinput(f"a-{i}"), tenant_id="a",
                                      kind=DISRUPTION))
            big_i = SolverInput(
                pods=[mkpod(f"b-{i}-{j}") for j in range(20)],
                nodes=[], nodepools=[pool()], zones=ZONES,
            )
            tickets.append(mux.submit(big_i, tenant_id="b", kind=DISRUPTION))
        svc.gate.set()
        for t in tickets:
            assert t.result(timeout=10)
        assert svc.cohorts == []
        assert mux.unresolved() == 0
    finally:
        mux.close()


def test_cohort_max_fail_closed():
    """A nonsensical cohort width is a config error at construction AND at
    the flag parser — never a silent fall-back to solo dispatch."""
    svc = FakeService()
    with pytest.raises(ValueError):
        TenantMux(svc, mkregistry(("a", 1.0)), cohort_max=0)
    svc.close()
    from karpenter_tpu.operator import options as opts
    with pytest.raises(SystemExit):
        opts.parse(["--solver-cohort-max", "0"])
    o = opts.parse([])
    assert o.solver_cohort is True  # default-on
    assert o.solver_cohort_max == 8


# --------------------------------------------------------- poison isolation


def test_poison_cohort_member_charges_only_its_tenant():
    """One poisoned member in a fused dispatch: only ITS tenant's breaker
    is charged, it replays on ITS oracle (the solve still lands), the
    co-member keeps its fused result, and the poison-replay counter names
    the victim."""
    svc = FakeCohortService(size=1, depth=1, gated=True,
                            fail_marker="poison")
    mux = TenantMux(svc, mkregistry(("a", 1.0), ("b", 1.0)),
                    breaker_threshold=3, breaker_probe_s=60.0,
                    own_service=True)
    poison0 = SOLVER_COHORT_POISON_REPLAYS.value(tenant="a")
    try:
        primer = mux.submit(mkinput("b-primer"), tenant_id="b",
                            kind=DISRUPTION)
        time.sleep(0.05)
        ta = mux.submit(mkinput("a-poison-0"), tenant_id="a",
                        kind=DISRUPTION)
        tb = mux.submit(mkinput("b-0"), tenant_id="b", kind=DISRUPTION)
        svc.gate.set()
        assert primer.result(timeout=10)
        ra = ta.result(timeout=10)  # oracle replay: a real SolverResult
        assert ra.claims and ra.claims[0].pod_uids == ["a-poison-0"]
        assert tb.result(timeout=10) == ("solved", "b", "b-0")
        assert svc.cohorts and len(svc.cohorts[0]) == 2, svc.cohorts
        # the replay rode a's oracle lane, not the shared downstream
        assert svc.order.count(("a", "a-poison-0")) == 1
        assert SOLVER_COHORT_POISON_REPLAYS.value(tenant="a") == poison0 + 1
        assert SOLVER_COHORT_POISON_REPLAYS.value(tenant="b") == 0
        st = mux.tenant_stats()
        assert st["b"]["breaker"] == "closed" and st["b"]["degraded"] == 0
        assert st["a"]["degraded"] >= 1
        assert st["a"]["failed"] == 0  # the poisoned solve still landed
        assert mux.unresolved() == 0
    finally:
        mux.close()


# ----------------------------------------------------- backend fusion parity


def _rand_inp(rng, tag, npods):
    pods = [mkpod(f"{tag}-{j}", cpu=rng.choice(["100m", "250m", "500m"]),
                  mem=rng.choice(["256Mi", "512Mi"]))
            for j in range(npods)]
    return SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)


@pytest.mark.parametrize("n", [1, 2, 4, 8])
def test_cohort_backend_parity_matches_solo(n):
    """Decision parity across cohort sizes: every fused member's
    SolverResult, explain fingerprint, and per-tenant metered h2d bytes
    are identical to a solo dispatch of the same input."""
    rng = random.Random(100 + n)
    npods = rng.choice([2, 3])
    tenants = [f"co{n}t{i}" for i in range(n)]
    inps = [dataclasses.replace(_rand_inp(rng, f"p{n}-{i}", npods),
                                tenant_id=tenants[i])
            for i in range(n)]
    obsexplain.configure(enabled=True, top_k=8)
    try:
        fused = TPUSolver()
        h2d0 = {t: TENANT_METER_H2D_BYTES.value(tenant=t) for t in tenants}
        fin = fused.solve_cohort_async(inps)
        outs = fin()
        h2d_fused = {t: TENANT_METER_H2D_BYTES.value(tenant=t) - h2d0[t]
                     for t in tenants}
        assert all(isinstance(o, SolverResult) for o in outs), outs
        assert fused.stats["fallback_solves"] == 0
        assert fused.stats["device_solves"] == n
        if n > 1:
            assert fused.stats["fused_dispatches"] == 1
            assert fused.stats["fused_members"] == n
        # map each member's explain entry by its first pod uid (solo runs
        # haven't populated the store yet)
        store = obsexplain.store()
        fused_fp = {}
        for i in range(n):
            hits = store.by_pod(inps[i].pods[0].meta.uid)
            assert len(hits) == 1, (i, len(hits))
            fused_fp[i] = hits[0]["fingerprint"]
            assert fused_fp[i] is not None

        solo = TPUSolver()
        for i in range(n):
            ref = solo.solve(inps[i])
            h2d_solo = solo.ledger.solve["h2d_bytes"]
            assert outs[i].placements == ref.placements, i
            assert outs[i].claims == ref.claims, i
            assert outs[i].errors == ref.errors, i
            fp = store.recent(1)[0]["fingerprint"]
            assert fp == fused_fp[i], i
            if n > 1:
                # fused attribution: each member is billed exactly the
                # bytes its solo dispatch physically uploads
                assert h2d_fused[tenants[i]] == h2d_solo, i
        assert solo.stats["fallback_solves"] == 0
    finally:
        obsexplain.configure(enabled=False)


def test_cohort_padding_adds_zero_ledger_bytes():
    """Satellite: padding a 3-member cohort to its batch bucket of 4 must
    move ZERO extra host->device bytes — the fused upload is exactly three
    members' worth on the TransferLedger."""
    rng = random.Random(7)
    inps = [dataclasses.replace(_rand_inp(rng, f"pad-{i}", 2),
                                tenant_id=f"pad{i}")
            for i in range(3)]
    solo = TPUSolver()
    solo.solve(inps[0])
    member_bytes = solo.ledger.total["h2d_bytes"]
    assert member_bytes > 0
    fused = TPUSolver()
    outs = fused.solve_cohort_async(inps)()
    assert all(isinstance(o, SolverResult) for o in outs)
    assert fused.stats["fused_members"] == 3
    assert fused.ledger.total["h2d_bytes"] == 3 * member_bytes


def test_pad_batch_is_device_side_only():
    """pad_batch replicates the last REAL lane on device: correct shapes
    and values, and — once its jit is warm — no host->device transfer at
    all (the transfer guard would throw)."""
    batched = tuple(
        jax.numpy.asarray(np.arange(6 * (k + 1), dtype=np.int32)
                          .reshape(3, 2 * (k + 1)))
        for k in range(2)
    )
    pad_batch(batched, 8)  # warm the shape's cached jit
    shifted = tuple(a + 1 for a in batched)
    with jax.transfer_guard("disallow"):
        out = pad_batch(shifted, 8)
    for a_in, a_out in zip(shifted, out):
        assert a_out.shape == (8,) + a_in.shape[1:]
        got = np.asarray(a_out)
        np.testing.assert_array_equal(got[:3], np.asarray(a_in))
        np.testing.assert_array_equal(
            got[3:], np.broadcast_to(got[2:3], (5,) + got.shape[1:]))
    # already at (or past) the bucket: the arrays pass through untouched
    assert all(a is b for a, b in zip(pad_batch(batched, 3), batched))
    assert all(a is b for a, b in zip(pad_batch(batched, 2), batched))
