"""Repair controller, TTL caches, pricing provider, options parsing."""

import pytest

from karpenter_tpu.api import wellknown as wk
from karpenter_tpu.controllers import store as st
from karpenter_tpu.operator import options as opts
from karpenter_tpu.operator.operator import new_kwok_operator
from karpenter_tpu.providers.cache import TTLCache
from karpenter_tpu.providers.pricing import PricingProvider
from karpenter_tpu.catalog.catalog import generate

from tests.test_e2e_kwok import FakeClock, mkpod, mkpool


class TestRepair:
    @pytest.fixture
    def op(self):
        clock = FakeClock()
        o = new_kwok_operator(clock=clock)
        o.clock = clock
        return o

    def _provision(self, op, n=1):
        op.store.create(st.NODEPOOLS, mkpool())
        from karpenter_tpu.api.objects import TopologySpreadConstraint

        tsc = TopologySpreadConstraint(
            max_skew=1, topology_key=wk.HOSTNAME_LABEL, label_selector={"app": "x"}
        )
        for i in range(n):
            op.store.create(
                st.PODS,
                mkpod(f"p{i}", cpu="200m", labels={"app": "x"},
                      topology_spread=[tsc] if n > 1 else []),
            )
        op.manager.settle()

    def test_unhealthy_node_repaired_after_toleration(self, op):
        self._provision(op)
        node = op.store.list(st.NODES)[0]
        node.set_condition("Ready", "False", op.clock())
        op.store.update(st.NODES, node)
        # not ripe yet (toleration 30m)
        op.manager.settle()
        assert op.store.try_get(st.NODES, node.meta.name) is not None
        op.clock.advance(31 * 60)
        op.manager.settle()
        nodes = op.store.list(st.NODES)
        assert all(n.meta.name != node.meta.name for n in nodes)  # replaced
        assert op.store.get(st.PODS, "p0").node_name  # pod rescheduled

    def test_circuit_breaker_on_mass_unhealthy(self, op):
        self._provision(op, n=3)
        nodes = op.store.list(st.NODES)
        assert len(nodes) == 3
        for n in nodes:  # 100% unhealthy > 20% breaker
            n.set_condition("Ready", "False", op.clock())
            op.store.update(st.NODES, n)
        op.clock.advance(31 * 60)
        op.manager.settle()
        assert len(op.store.list(st.NODES)) == 3  # breaker held


class TestTTLCache:
    def test_expiry(self):
        clock = FakeClock()
        c = TTLCache(ttl_s=10, clock=clock)
        c.set("k", 1)
        assert c.get("k") == 1
        clock.advance(11)
        assert c.get("k") is None

    def test_get_or_compute(self):
        c = TTLCache(ttl_s=100, clock=FakeClock())
        calls = []
        assert c.get_or_compute("k", lambda: calls.append(1) or 42) == 42
        assert c.get_or_compute("k", lambda: calls.append(1) or 43) == 42
        assert len(calls) == 1


class TestPricing:
    def test_static_fallback_and_live_updates(self):
        clock = FakeClock()
        catalog = generate()
        it = catalog[0]
        o = it.offerings[0]
        live = {}
        p = PricingProvider(catalog, live_source=lambda: dict(live), clock=clock)
        assert p.price(it.name, o.zone, o.capacity_type) == o.price
        # live spot movement applies after refresh
        live[(it.name, o.zone, o.capacity_type)] = 9.99
        assert p.price(it.name, o.zone, o.capacity_type) == o.price  # not yet
        clock.advance(13 * 3600)
        assert p.refresh_if_due()
        assert p.price(it.name, o.zone, o.capacity_type) == 9.99

    def test_source_failure_keeps_static(self):
        def boom():
            raise RuntimeError("api down")

        catalog = generate()
        p = PricingProvider(catalog, live_source=boom)
        assert not p.refresh()
        it = catalog[0]
        assert p.price(it.name, it.offerings[0].zone, it.offerings[0].capacity_type) is not None

    def test_apply_rewrites_offerings(self):
        catalog = generate()
        it = catalog[0]
        key = (it.name, it.offerings[0].zone, it.offerings[0].capacity_type)
        p = PricingProvider(catalog, live_source=lambda: {key: 1.23})
        p._last_refresh = -1e12
        p.refresh()
        p.apply([it])
        assert it.offerings[0].price == 1.23


class TestOptions:
    def test_defaults(self):
        o = opts.parse([])
        assert o.batch_idle_duration_s == 1.0
        assert o.solver_backend == "tpu"
        assert o.kube_client_qps == 200

    def test_argv_overrides(self):
        o = opts.parse(["--solver-backend", "reference", "--batch-idle-duration-s", "0"])
        assert o.solver_backend == "reference"
        assert o.batch_idle_duration_s == 0.0

    def test_env_layer(self, monkeypatch):
        monkeypatch.setenv("KARPENTER_METRICS_PORT", "9999")
        o = opts.parse([])
        assert o.metrics_port == 9999

    def test_feature_gates(self):
        o = opts.parse(["--feature-gates", "SpotToSpotConsolidation=true,Other=false"])
        assert o.gates() == {"SpotToSpotConsolidation": True, "Other": False}
