"""Device-resident argument arena: parity + transfer-ledger invariants.

ISSUE 3 acceptance: arena-on and arena-off solves are bit-identical across
mutation / exact-hit / bucket-change / fallback-replay sequences, and the
TransferLedger PROVES the transfer claims instead of timing them — an exact
encode-cache hit uploads zero bytes, a steady-state node-delta solve pays
exactly one packed message carrying only the stale entries, and a
ResilientSolver fallback replay invalidates residency before reuse.
"""

import dataclasses

from karpenter_tpu import faults
from karpenter_tpu.provisioning.scheduler import SolverInput
from karpenter_tpu.solver.backend import ReferenceSolver, TPUSolver
from karpenter_tpu.solver.resilient import ResilientSolver
from karpenter_tpu.solver.tpu.ffd import ARG_SPEC

from tests.test_e2e_kwok import FakeClock
from tests.test_solver_parity import ZONES, mkpod, pool

_CPUS = [
    "150m", "250m", "300m", "500m", "700m", "900m", "1", "1100m", "1300m",
    "1500m", "1700m", "1900m", "2", "2100m", "2300m", "2500m", "2700m",
    "2900m", "3", "3100m",
]


def _inp(n=40, specs=1, prefix="p"):
    """`specs` distinct pod sizes: specs=1 stays in the smallest shape
    bucket; specs=20 pushes the run/group axes past the first bucket edge
    (Sp/Gp: 16), forcing a different arena bucket."""
    pods = [mkpod(f"{prefix}{i}", cpu=_CPUS[i % specs]) for i in range(n)]
    return SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)


def _assert_same(a, b, tag=""):
    assert a.placements == b.placements, f"{tag}: placements diverge"
    assert set(a.errors) == set(b.errors), f"{tag}: errors diverge"
    assert len(a.claims) == len(b.claims), f"{tag}: claim count diverges"
    for i, (ca, cb) in enumerate(zip(a.claims, b.claims)):
        assert ca.pod_uids == cb.pod_uids, f"{tag}: claim {i} pods diverge"
        assert ca.nodepool == cb.nodepool, f"{tag}: claim {i} pool diverges"
        assert sorted(ca.instance_type_names) == sorted(cb.instance_type_names), (
            f"{tag}: claim {i} type set diverges"
        )


# -- parity ------------------------------------------------------------------


def test_parity_across_mutate_hit_and_bucket_change():
    """The full residency lifecycle — cold, exact hit, pod-delta mutation,
    bucket change, return to the first bucket — decides identically with the
    arena on and off."""
    on, off = TPUSolver(), TPUSolver(arena=False)
    a = _inp(40)
    seq = [
        ("cold", a),
        ("exact-hit", a),
        ("mutate", dataclasses.replace(a, pods=a.pods[:-3])),
        ("bucket-change", _inp(60, specs=20, prefix="q")),
        ("back-to-first-bucket", a),
    ]
    for tag, inp in seq:
        _assert_same(on.solve(inp), off.solve(inp), tag)
    st = on.arena.stats
    # the sequence must actually exercise every hit class, or the parity
    # proof proves nothing
    assert st["full_uploads"] >= 2, st  # cold + bucket change
    assert st["delta_uploads"] >= 1, st  # the pod-delta mutation
    assert st["exact_hits"] >= 1, st
    assert len(on.arena._buckets) == 2  # both shape buckets resident


def test_bucket_return_is_exact_hit():
    """Leaving a bucket and coming back must not re-upload: buckets hold
    residency independently (a control loop alternates surge shapes)."""
    s = TPUSolver()
    a, b = _inp(40), _inp(60, specs=20, prefix="q")
    s.solve(a)
    s.solve(b)
    hits_before = s.arena.stats["exact_hits"]
    s.solve(a)
    assert s.arena.stats["exact_hits"] == hits_before + 1
    assert s.ledger.solve["h2d_bytes"] == 0


# -- ledger invariants -------------------------------------------------------


def test_exact_hit_uploads_zero_bytes():
    s = TPUSolver()
    inp = _inp(40)
    s.solve(inp)
    assert s.ledger.outcomes["full_upload"] == 1
    full_bytes = s.ledger.solve["h2d_bytes"]
    assert full_bytes > 0 and s.ledger.solve["h2d_msgs"] == 1
    s.solve(inp)  # unchanged input: exact encode-cache hit
    assert s.ledger.solve["h2d_bytes"] == 0
    assert s.ledger.solve["h2d_arrays"] == 0
    assert s.ledger.solve["h2d_msgs"] == 0
    assert s.ledger.outcomes["exact_hit"] == 1
    # decode still fetched (the ledger counts BOTH directions)
    assert s.ledger.solve["d2h_bytes"] > 0
    assert s.ledger.arena_hit_rate == 0.5


def test_delta_solve_pays_one_packed_message():
    """A pod-count mutation inside one shape bucket re-uploads ONLY the
    stale entries, packed into a single message, strictly smaller than the
    cold upload."""
    s = TPUSolver()
    inp = _inp(40)
    s.solve(inp)
    full = dict(s.ledger.solve)
    assert full["h2d_arrays"] == len(ARG_SPEC)
    s.solve(dataclasses.replace(inp, pods=inp.pods[:-3]))
    delta = dict(s.ledger.solve)
    assert s.ledger.outcomes["delta_upload"] == 1
    assert delta["h2d_msgs"] == 1  # ONE packed buffer, not per-array puts
    assert 1 <= delta["h2d_arrays"] < len(ARG_SPEC)  # only stale entries
    assert 0 < delta["h2d_bytes"] < full["h2d_bytes"]


def test_arena_off_uploads_per_array():
    """The debug escape hatch (--solver-arena=false) ships every array as
    its own message — the behavior the arena exists to replace."""
    from karpenter_tpu.solver import backend, encode as em

    # cold caches: earlier tests leave the core/device caches warm, which
    # would (correctly) skim static-core uploads even with the arena off
    em._CORE_CACHE.clear()
    backend._DEV_CACHE.clear()
    s = TPUSolver(arena=False)
    s.solve(_inp(40))
    assert s.ledger.solve["h2d_msgs"] == len(ARG_SPEC)
    assert s.ledger.outcomes == {
        "exact_hit": 0, "delta_upload": 0, "full_upload": 0
    }
    assert s.ledger.arena_hit_rate == 0.0


# -- fallback-replay invalidation --------------------------------------------


def test_fallback_replay_invalidates_arena():
    """A device failure routes to the fallback AND drops residency: the
    replay (and the next device solve) must not trust buffers a failed
    dispatch may have left in an unknown state."""
    inner = TPUSolver()
    rs = ResilientSolver(inner, fallbacks=[ReferenceSolver()],
                         clock=FakeClock())
    off = TPUSolver(arena=False)
    inp = _inp(40)
    warm = rs.solve(inp)
    _assert_same(warm, off.solve(inp), "warm")
    assert inner.arena._buckets  # residency established

    plan = faults.FaultPlan(seed=0)
    plan.fail_n("solver.device_dispatch", 1)
    with faults.active(plan):
        replayed = rs.solve(inp)
    assert plan.fired["solver.device_dispatch"] == 1
    assert inner.arena.stats["invalidations"] >= 1
    assert not inner.arena._buckets  # residency dropped before replay
    _assert_same(replayed, warm, "fallback-replay")

    # device recovered: next solve pays a full packed upload, not a hit
    full_before = inner.arena.stats["full_uploads"]
    recovered = rs.solve(inp)
    assert inner.arena.stats["full_uploads"] == full_before + 1
    assert inner.ledger.solve["h2d_msgs"] == 1
    _assert_same(recovered, warm, "recovered")


def test_fallback_replay_invalidates_shard_residency():
    """Multi-device (virtual mesh) case of the invalidation contract: a
    failed dispatch must drop the per-device argument shards AND the
    block-boundary carries — the sharded path's per-device checkpoint
    rings — before the fallback replay, and the recovered device solve
    re-establishes both from scratch."""
    inner = TPUSolver(shards=8)
    rs = ResilientSolver(inner, fallbacks=[ReferenceSolver()],
                         clock=FakeClock())
    inp = _inp(40, specs=20)  # 20 runs: enough to split across the mesh
    warm = rs.solve(inp)
    assert inner.stats["sharded_solves"] >= 1, inner.stats
    assert inner.arena._shards  # block-boundary carries recorded
    assert inner.arena._buckets  # per-device argument residency established

    plan = faults.FaultPlan(seed=0)
    plan.fail_n("solver.device_dispatch", 1)
    with faults.active(plan):
        replayed = rs.solve(inp)
    assert plan.fired["solver.device_dispatch"] == 1
    assert inner.arena.stats["invalidations"] >= 1
    assert not inner.arena._shards  # per-device checkpoint rings dropped
    assert not inner.arena._buckets  # per-device argument shards dropped
    _assert_same(replayed, warm, "sharded fallback-replay")

    # device recovered: the next sharded solve re-uploads and re-records
    recovered = rs.solve(inp)
    _assert_same(recovered, warm, "sharded recovered")
    assert inner.arena._shards


def test_explicit_invalidate_is_safe_anytime():
    s = TPUSolver()
    s.invalidate_arena()  # empty arena: no-op beyond the counter
    inp = _inp(40)
    r1 = s.solve(inp)
    s.invalidate_arena()
    r2 = s.solve(inp)
    assert s.arena.stats["full_uploads"] == 2  # re-upload, same answer
    _assert_same(r1, r2, "post-invalidate")
