"""Reference scheduler behavior (karpenter_tpu/provisioning/scheduler.py).

Scenario coverage mirrors the reference's scheduling test themes
(SURVEY.md §4: suites drive the real provisioner against fakes): FFD packing,
nodeSelector/requirements, taints/tolerations, existing-node reuse, zonal
topology spread, anti-affinity, nodepool weights and limits.
"""

import pytest

from karpenter_tpu.api import wellknown as wk
from karpenter_tpu.api.objects import (
    ObjectMeta,
    Pod,
    PodAffinityTerm,
    Taint,
    Toleration,
    TopologySpreadConstraint,
)
from karpenter_tpu.catalog.catalog import CatalogSpec, generate
from karpenter_tpu.provisioning.scheduler import (
    ExistingNode,
    NodePoolSpec,
    SolverInput,
    solve,
)
from karpenter_tpu.scheduling.requirements import IN, Requirement, Requirements
from karpenter_tpu.utils.resources import Resources

CATALOG = generate(CatalogSpec())
ZONES = ("zone-1a", "zone-1b", "zone-1c")


def mkpod(name, cpu="1", mem="1Gi", labels=None, **kw):
    return Pod(
        meta=ObjectMeta(name=name, uid=name, labels=labels or {}),
        requests=Resources.parse({"cpu": cpu, "memory": mem}),
        **kw,
    )


def default_pool(name="default", weight=0, reqs=None, taints=None, limits=None, types=None):
    r = Requirements.of(Requirement.create(wk.NODEPOOL_LABEL, IN, [name]))
    if reqs:
        r = r.union(reqs)
    return NodePoolSpec(
        name=name,
        weight=weight,
        requirements=r,
        taints=taints or [],
        instance_types=types if types is not None else CATALOG,
        limits=limits or Resources(),
    )


def run(pods, pools=None, nodes=None, **kw):
    return solve(
        SolverInput(
            pods=pods,
            nodes=nodes or [],
            nodepools=pools or [default_pool()],
            zones=ZONES,
            **kw,
        )
    )


class TestBasicPacking:
    def test_single_pod_gets_a_claim(self):
        res = run([mkpod("p1")])
        assert not res.errors
        assert len(res.claims) == 1
        assert res.placements["p1"][0] == "claim"
        assert len(res.claims[0].instance_type_names) > 0

    def test_identical_pods_pack_onto_one_claim(self):
        pods = [mkpod(f"p{i}", cpu="500m", mem="512Mi") for i in range(8)]
        res = run(pods)
        assert not res.errors
        assert len(res.claims) == 1
        assert len(res.claims[0].pod_uids) == 8

    def test_ffd_orders_big_pods_first(self):
        small = mkpod("small", cpu="100m")
        big = mkpod("big", cpu="8")
        res = run([small, big])
        assert not res.errors
        # big processed first => it's the first pod of the first claim
        assert res.claims[0].pod_uids[0] == "big"

    def test_huge_pod_unschedulable(self):
        res = run([mkpod("huge", cpu="10000")])  # 10k cores fits nothing
        assert "huge" in res.errors

    def test_pod_count_limit_respected(self):
        # m5.medium allows 29 pods; tiny pods must spread across claims by pods capacity
        tiny = [mkpod(f"t{i}", cpu="1m", mem="1Mi") for i in range(40)]
        small_types = [it for it in CATALOG if it.name == "m5.medium"]
        res = run(tiny, pools=[default_pool(types=small_types)])
        assert not res.errors
        # 29 - daemonset(0) pods per medium, 40 pods => 2 claims
        assert len(res.claims) == 2

    def test_requests_accumulate(self):
        pods = [mkpod(f"p{i}", cpu="2", mem="2Gi") for i in range(3)]
        res = run(pods)
        assert res.claims[0].requests.get_("cpu") == 6000


class TestConstraints:
    def test_node_selector_filters_types(self):
        pod = mkpod("p", node_selector={wk.ARCH_LABEL: "arm64"})
        res = run([pod])
        assert not res.errors
        for name in res.claims[0].instance_type_names:
            it = next(t for t in CATALOG if t.name == name)
            assert it.requirements[wk.ARCH_LABEL].values_list() == ["arm64"]

    def test_impossible_selector_errors(self):
        pod = mkpod("p", node_selector={wk.ARCH_LABEL: "riscv"})
        res = run([pod])
        assert "p" in res.errors

    def test_gt_requirement(self):
        pod = Pod(
            meta=ObjectMeta(name="p", uid="p"),
            requests=Resources.parse({"cpu": "1"}),
            node_affinity=[
                Requirements.of(Requirement.create("karpenter.tpu/instance-generation", "Gt", ["6"]))
            ],
        )
        res = run([pod])
        assert not res.errors
        for name in res.claims[0].instance_type_names:
            it = next(t for t in CATALOG if t.name == name)
            gen = int(it.requirements["karpenter.tpu/instance-generation"].values_list()[0])
            assert gen > 6

    def test_taints_require_toleration(self):
        taint = Taint(key="dedicated", value="gpu", effect=wk.EFFECT_NO_SCHEDULE)
        pool = default_pool(taints=[taint])
        res = run([mkpod("p")], pools=[pool])
        assert "p" in res.errors
        tol = Toleration(key="dedicated", value="gpu", effect=wk.EFFECT_NO_SCHEDULE)
        res2 = run([mkpod("p", tolerations=[tol])], pools=[pool])
        assert not res2.errors

    def test_incompatible_pods_get_separate_claims(self):
        a = mkpod("a", node_selector={wk.ARCH_LABEL: "amd64"})
        b = mkpod("b", node_selector={wk.ARCH_LABEL: "arm64"})
        res = run([a, b])
        assert not res.errors
        assert len(res.claims) == 2


class TestExistingNodes:
    def mknode(self, name, zone="zone-1a", cpu="4", mem="16Gi", pods=100, labels=None, taints=None):
        lab = {
            wk.ZONE_LABEL: zone,
            wk.HOSTNAME_LABEL: name,
            wk.CAPACITY_TYPE_LABEL: "on-demand",
            wk.ARCH_LABEL: "amd64",
        }
        lab.update(labels or {})
        free = Resources.parse({"cpu": cpu, "memory": mem})
        free["pods"] = pods
        return ExistingNode(id=name, labels=lab, taints=taints or [], free=free)

    def test_existing_node_preferred_over_new_claim(self):
        res = run([mkpod("p")], nodes=[self.mknode("n1")])
        assert res.placements["p"] == ("node", "n1")
        assert not res.claims

    def test_existing_node_full_spills_to_claim(self):
        res = run([mkpod("p", cpu="8")], nodes=[self.mknode("n1", cpu="4")])
        assert res.placements["p"][0] == "claim"

    def test_existing_node_label_mismatch(self):
        pod = mkpod("p", node_selector={wk.ARCH_LABEL: "arm64"})
        res = run([pod], nodes=[self.mknode("n1")])
        assert res.placements["p"][0] == "claim"

    def test_existing_node_taint(self):
        taint = Taint(key="x", value="y", effect=wk.EFFECT_NO_SCHEDULE)
        res = run([mkpod("p")], nodes=[self.mknode("n1", taints=[taint])])
        assert res.placements["p"][0] == "claim"


class TestTopologySpread:
    def tsc(self, skew=1, key=wk.ZONE_LABEL):
        return TopologySpreadConstraint(
            max_skew=skew, topology_key=key, label_selector={"app": "web"}
        )

    def test_zone_spread_across_claims(self):
        pods = [
            mkpod(f"p{i}", cpu="1", labels={"app": "web"}, topology_spread=[self.tsc()])
            for i in range(6)
        ]
        res = run(pods)
        assert not res.errors
        zones = []
        for c in res.claims:
            zr = c.requirements.get(wk.ZONE_LABEL)
            assert zr is not None and len(zr.values_list()) == 1
            zones.extend(zr.values_list() * len(c.pod_uids))
        from collections import Counter

        counts = Counter(zones)
        assert max(counts.values()) - min(counts.get(z, 0) for z in ZONES) <= 1

    def test_hostname_spread_forces_one_pod_per_claim(self):
        pods = [
            mkpod(
                f"p{i}",
                cpu="100m",
                labels={"app": "web"},
                topology_spread=[self.tsc(key=wk.HOSTNAME_LABEL)],
            )
            for i in range(3)
        ]
        res = run(pods)
        assert not res.errors
        assert len(res.claims) == 3
        assert all(len(c.pod_uids) == 1 for c in res.claims)


class TestAffinity:
    def test_hostname_anti_affinity_separates(self):
        term = PodAffinityTerm(label_selector={"app": "db"}, topology_key=wk.HOSTNAME_LABEL, anti=True)
        pods = [
            mkpod(f"p{i}", cpu="100m", labels={"app": "db"}, affinity_terms=[term])
            for i in range(3)
        ]
        res = run(pods)
        assert not res.errors
        assert len(res.claims) == 3

    def test_zone_affinity_coschedules(self):
        term = PodAffinityTerm(label_selector={"app": "web"}, topology_key=wk.ZONE_LABEL)
        pods = [
            mkpod(f"p{i}", cpu="1", labels={"app": "web"}, affinity_terms=[term])
            for i in range(4)
        ]
        res = run(pods)
        assert not res.errors
        zones = set()
        for c in res.claims:
            zr = c.requirements.get(wk.ZONE_LABEL)
            if zr:
                zones.update(zr.values_list())
        assert len(zones) <= 1 or not zones


class TestNodePools:
    def test_weight_order(self):
        heavy = default_pool("heavy", weight=100)
        light = default_pool("light", weight=1)
        res = run([mkpod("p")], pools=[light, heavy])
        assert res.claims[0].nodepool == "heavy"

    def test_limits_block_new_claims(self):
        pool = default_pool("capped", limits=Resources.parse({"cpu": "1"}))
        pool.usage = Resources.parse({"cpu": "2"})
        res = run([mkpod("p")], pools=[pool])
        assert "p" in res.errors

    def test_fallback_to_lower_weight_on_incompatibility(self):
        arm_only = default_pool(
            "arm", weight=100, reqs=Requirements.of(Requirement.create(wk.ARCH_LABEL, IN, ["arm64"]))
        )
        anything = default_pool("any", weight=1)
        pod = mkpod("p", node_selector={wk.ARCH_LABEL: "amd64"})
        res = run([pod], pools=[arm_only, anything])
        assert not res.errors
        assert res.claims[0].nodepool == "any"


class TestDaemonSets:
    def test_daemonset_overhead_reserved(self):
        ds = mkpod("ds", cpu="1", mem="1Gi")
        # pod that fits a m5.large (2cpu) alone but not with the daemonset
        pod = mkpod("p", cpu="1500m", mem="1Gi")
        types = [it for it in CATALOG if it.name in ("m5.large", "m5.xlarge")]
        res = run([pod], pools=[default_pool(types=types)], daemonset_pods=[ds])
        assert not res.errors
        assert res.claims[0].instance_type_names == ["m5.xlarge"]


class TestReviewRegressions:
    """Regressions for the round-1 code-review findings."""

    def test_exists_requires_label_present_on_node(self):
        from karpenter_tpu.scheduling.requirements import EXISTS

        pod = Pod(
            meta=ObjectMeta(name="p", uid="p"),
            requests=Resources.parse({"cpu": "1"}),
            node_affinity=[Requirements.of(Requirement.create("accelerator-type", EXISTS))],
        )
        node = TestExistingNodes().mknode("n1")  # has no accelerator-type label
        res = run([pod], nodes=[node])
        # must NOT land on n1; no instance type defines the label either
        assert res.placements.get("p", ("claim", 0))[0] != "node"

    def test_or_node_affinity_terms(self):
        # kube semantics: terms are OR'd; folding them would intersect zones to {}
        pod = Pod(
            meta=ObjectMeta(name="p", uid="p"),
            requests=Resources.parse({"cpu": "1"}),
            node_affinity=[
                Requirements.of(Requirement.create(wk.ZONE_LABEL, IN, ["zone-1a"])),
                Requirements.of(Requirement.create(wk.ZONE_LABEL, IN, ["zone-1b"])),
            ],
        )
        res = run([pod])
        assert not res.errors
        zr = res.claims[0].requirements[wk.ZONE_LABEL]
        assert zr.values_list() == ["zone-1a"]  # first alternative wins

    def test_contradictory_gt_lt_rejected(self):
        pod = Pod(
            meta=ObjectMeta(name="p", uid="p"),
            requests=Resources.parse({"cpu": "1"}),
            node_affinity=[
                Requirements.of(
                    Requirement.create("custom-gen", "Gt", ["5"]),
                    Requirement.create("custom-gen", "Lt", ["3"]),
                )
            ],
        )
        res = run([pod])
        assert "p" in res.errors

    def test_spread_sees_pods_placed_earlier_this_solve(self):
        # Pod A (no TSC) lands in some zone; pod B's TSC group materializes
        # later and must count A.
        a = mkpod("a", cpu="8", labels={"app": "x"},
                  node_selector={wk.ZONE_LABEL: "zone-1a"})
        tsc = TopologySpreadConstraint(max_skew=1, topology_key=wk.ZONE_LABEL,
                                       label_selector={"app": "x"})
        b = mkpod("b", cpu="1", labels={"app": "x"}, topology_spread=[tsc])
        c = mkpod("c", cpu="1", labels={"app": "x"}, topology_spread=[tsc])
        res = run([a, b, c])
        assert not res.errors
        # a in zone-1a counts: b and c must avoid stacking zone-1a beyond skew
        zone_counts = {}
        for cl in res.claims:
            zr = cl.requirements.get(wk.ZONE_LABEL)
            if zr and len(zr.values_list()) == 1:
                zone_counts[zr.values_list()[0]] = zone_counts.get(zr.values_list()[0], 0) + len(
                    [u for u in cl.pod_uids]
                )
        assert zone_counts.get("zone-1a", 0) <= 2  # a + at most one of b/c

    def test_affinity_sees_pods_placed_earlier_this_solve(self):
        anchor = mkpod("anchor", cpu="8", labels={"app": "db"},
                       node_selector={wk.ZONE_LABEL: "zone-1b"})
        term = PodAffinityTerm(label_selector={"app": "db"}, topology_key=wk.ZONE_LABEL)
        follower = mkpod("f", cpu="1", labels={"other": "1"}, affinity_terms=[term])
        res = run([anchor, follower])
        assert not res.errors
        # follower must co-locate with anchor's zone
        f_claim = res.claims[res.placements["f"][1]]
        assert f_claim.requirements[wk.ZONE_LABEL].values_list() == ["zone-1b"]
