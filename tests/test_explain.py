"""Decision provenance & SLO engine (ISSUE 12).

Covers the whole explain stack end to end:

- wire bit-parity: the device kernel (tpu/ffd.explain_pack) and the host
  deriver (obs/explain.reason_codes + rejection_table) produce the SAME
  int32 words on randomized tables, including the zero-width zone/ct and
  fewer-nodes-than-top-k edges, plus the uint16 overflow carve-out;
- 3-way record parity: oracle / native / TPU captures fingerprint
  bit-identically on randomized scenarios, through the relax ladder, the
  class pass (preemption + gangs), mesh-sharded solves and checkpointed
  resume (the carve-outs host-derive but must still match);
- off-path inertness: explain off moves zero extra d2h bytes and the
  disabled hooks allocate nothing;
- ExplainStore semantics: lazy materialization, merge-put, ring eviction,
  by-pod lookup;
- SLO engine: burn-rate windows under an injected clock, page/warn/ok
  states, objective-spec parsing, trace feed + tenant metering;
- operator surface: /debug/explain + /debug/trace filters (400 on bad
  params, 404 on unknown solve), /healthz slo object;
- flight-recorder dump pruning (--flight-recorder-keep).
"""

import gc
import json
import random
import sys
import urllib.error
import urllib.request

import numpy as np
import pytest

from karpenter_tpu.metrics.registry import (
    SOLVER_EXPLAIN_WIDE,
    TENANT_METER_D2H_BYTES,
    TENANT_METER_SOLVES,
)
from karpenter_tpu.obs import explain as obsexplain
from karpenter_tpu.obs import slo as obsslo
from karpenter_tpu.provisioning.scheduler import SolverInput
from karpenter_tpu.solver import scheduling_class as sc
from karpenter_tpu.solver.backend import ReferenceSolver, TPUSolver
from karpenter_tpu.solver.encode import encode, quantize_input
from karpenter_tpu.solver.native import NativeSolver
from karpenter_tpu.solver.tpu import ffd

from tests.test_scheduling_class import gang_labels, mknode, victim
from tests.test_solver_parity import ZONES, mkpod, pool


@pytest.fixture(autouse=True)
def _explain_defaults():
    """Every test starts and ends with the production defaults."""
    obsexplain.configure(enabled=False)
    obsslo.configure()
    sc.configure(preemption=True, gang=True)
    yield
    obsexplain.configure(enabled=False)
    obsslo.configure()
    sc.configure(preemption=True, gang=True)


def _capture_one(solver, inp, quantized=False):
    """Solve with explain on; return (result, the LAST stored entry,
    materialized)."""
    obsexplain.configure(enabled=True, top_k=8)
    res = solver.solve(quantize_input(inp) if quantized else inp)
    ents = obsexplain.store().recent(1)
    assert ents, "explain enabled but nothing captured"
    return res, ents[0]


def _assert_three_way(inp, k=8):
    """Oracle / native / TPU captures must fingerprint identically."""
    legs = {}
    for name, solver, q in (
        ("oracle", ReferenceSolver(), True),
        ("native", NativeSolver(), False),
        ("tpu", TPUSolver(), False),
    ):
        _, ent = _capture_one(solver, inp, quantized=q)
        legs[name] = ent
    base = legs["oracle"]
    for name in ("native", "tpu"):
        assert legs[name]["fingerprint"] == base["fingerprint"], (
            f"{name} diverges from oracle:\n"
            + "\n".join(obsexplain.diff_records(
                base["record"], legs[name]["record"])[:12])
        )
    return legs


# ---------------------------------------------------------------------------
# Wire bit-parity: numpy twin vs device kernel
# ---------------------------------------------------------------------------


class TestWireBitParity:
    def _random_tables(self, rng, G, E, S, R=2, Z=2, C=2):
        t = {
            "take_e": rng.integers(0, 3, size=(S, E), dtype=np.int32),
            "run_group": rng.integers(0, G, size=S, dtype=np.int32),
            "group_req": rng.integers(0, 4, size=(G, R), dtype=np.int32),
            "node_free": rng.integers(0, 16, size=(E, R), dtype=np.int32),
            "node_compat": rng.random((G, E)) < 0.8,
            "node_zone": rng.integers(-1, Z, size=E, dtype=np.int32),
            "node_ct": rng.integers(-1, C, size=E, dtype=np.int32),
            "group_zone": rng.random((G, Z)) < 0.7,
            "group_ct": rng.random((G, C)) < 0.7,
            "group_topo": rng.random(G) < 0.2,
            "group_aff": rng.random(G) < 0.2,
        }
        return t

    def _device(self, t, G, E, k):
        """Pad + dispatch exactly like backend._device_explain."""
        Gp = 1 << (max(G, 1) - 1).bit_length()
        Z = max(1, t["group_zone"].shape[1])
        C = max(1, t["group_ct"].shape[1])
        R = t["group_req"].shape[1]
        gr = np.zeros((Gp, R), np.int32)
        gr[:G] = t["group_req"]
        nc = np.zeros((Gp, E), bool)
        nc[:G] = t["node_compat"]
        gz = np.zeros((Gp, Z), bool)
        gz[:G, : t["group_zone"].shape[1]] = t["group_zone"]
        gct = np.zeros((Gp, C), bool)
        gct[:G, : t["group_ct"].shape[1]] = t["group_ct"]
        gt = np.zeros(Gp, bool)
        gt[:G] = t["group_topo"]
        ga = np.zeros(Gp, bool)
        ga[:G] = t["group_aff"]
        flat = np.asarray(ffd.explain_pack(
            t["take_e"], t["run_group"], gr, t["node_free"], nc,
            t["node_zone"], t["node_ct"], gz, gct, gt, ga,
            np.int32(E), np.int32(G), top_k=k,
        ))
        assert flat.shape[0] == ffd.explain_words(Gp, k)
        return ffd.unpack_explain(flat, G)

    @pytest.mark.parametrize("seed", [0, 1, 2, 3])
    def test_randomized_tables_bit_equal(self, seed):
        rng = np.random.default_rng(seed)
        G, E, S, k = (int(rng.integers(1, 9)), int(rng.integers(1, 20)),
                      int(rng.integers(1, 30)), int(rng.integers(1, 6)))
        t = self._random_tables(rng, G, E, S)
        codes = obsexplain.reason_codes(**t)
        h_rej, h_words = obsexplain.rejection_table(codes, k)
        overflow, d_rej, d_words = self._device(t, G, E, k)
        assert not overflow
        np.testing.assert_array_equal(h_rej, d_rej)
        np.testing.assert_array_equal(h_words, d_words)

    def test_zero_width_zone_ct_axes(self):
        rng = np.random.default_rng(7)
        t = self._random_tables(rng, 3, 5, 8, Z=2, C=2)
        t["group_zone"] = np.zeros((3, 0), bool)
        t["group_ct"] = np.zeros((3, 0), bool)
        t["node_zone"] = np.full(5, -1, np.int32)
        t["node_ct"] = np.full(5, -1, np.int32)
        codes = obsexplain.reason_codes(**t)
        h_rej, h_words = obsexplain.rejection_table(codes, 4)
        overflow, d_rej, d_words = self._device(t, 3, 5, 4)
        assert not overflow
        np.testing.assert_array_equal(h_rej, d_rej)
        np.testing.assert_array_equal(h_words, d_words)

    def test_fewer_nodes_than_top_k_pads_empty(self):
        rng = np.random.default_rng(9)
        t = self._random_tables(rng, 2, 3, 4)
        k = 8  # > E: both sides must pad the trailing slots with -1
        codes = obsexplain.reason_codes(**t)
        h_rej, h_words = obsexplain.rejection_table(codes, k)
        assert h_words.shape == (2, k)
        overflow, d_rej, d_words = self._device(t, 2, 3, k)
        assert d_words.shape == (2, k)
        np.testing.assert_array_equal(h_words, d_words)
        assert (h_words[:, 3:] == -1).all()

    def test_placed_node_is_always_feasible(self):
        # one group, one node, resources exhausted by its own pods: the
        # node cannot fit one more, but the group landed there — feasible
        t = {
            "take_e": np.array([[2]], np.int32),
            "run_group": np.array([0], np.int32),
            "group_req": np.array([[4]], np.int32),
            "node_free": np.array([[8]], np.int32),
            "node_compat": np.ones((1, 1), bool),
            "node_zone": np.array([-1], np.int32),
            "node_ct": np.array([-1], np.int32),
            "group_zone": np.zeros((1, 0), bool),
            "group_ct": np.zeros((1, 0), bool),
            "group_topo": np.zeros(1, bool),
            "group_aff": np.zeros(1, bool),
        }
        codes = obsexplain.reason_codes(**t)
        assert codes[0, 0] == obsexplain.REASON_FEASIBLE

    def test_uint16_overflow_carves_out_to_host(self):
        """A node axis above uint16 must skip the device table (counted by
        SOLVER_EXPLAIN_WIDE) — the host deriver recomputes at full width."""
        solver = TPUSolver()

        class _Out:
            take_e = np.zeros((1, 0x10000 + 1), np.int32)

        before = SOLVER_EXPLAIN_WIDE.value()
        assert solver._device_explain(None, _Out()) is None
        assert SOLVER_EXPLAIN_WIDE.value() == before + 1


# ---------------------------------------------------------------------------
# 3-way record parity on full solves
# ---------------------------------------------------------------------------


class TestThreeWayParity:
    @pytest.mark.parametrize("seed", [0, 1])
    def test_randomized_basic(self, seed):
        rng = random.Random(seed)
        pods = [
            mkpod(f"p{i:03d}", cpu=f"{rng.choice([250, 500, 1000, 2000])}m",
                  mem=f"{rng.choice([256, 512, 1024, 4096])}Mi")
            for i in range(30)
        ]
        inp = SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)
        legs = _assert_three_way(inp)
        rec = legs["tpu"]["record"]
        assert len(rec["pods"]) == 30
        assert legs["tpu"]["annotations"]["source"] == "device"
        assert legs["oracle"]["annotations"]["source"] == "host"

    def test_unschedulable_pods_surface_as_unplaced(self):
        pods = [mkpod("ok", cpu="500m"),
                mkpod("huge", cpu="999")]  # no catalog type fits
        inp = SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)
        legs = _assert_three_way(inp)
        assert "huge" in legs["tpu"]["record"]["unplaced"]
        assert legs["tpu"]["record"]["pods"]["huge"]["chosen"] is None

    def test_relax_ladder_leg_captures_and_matches(self):
        from karpenter_tpu.api.objects import TopologySpreadConstraint

        sel = {"app": "soft"}
        pods = [
            mkpod(f"s{i}", labels=dict(sel), topology_spread=[
                TopologySpreadConstraint(
                    max_skew=1, topology_key="topology.kubernetes.io/zone",
                    label_selector=sel, when_unsatisfiable="ScheduleAnyway")
            ])
            for i in range(3)
        ]
        inp = SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)
        obsexplain.configure(enabled=True, top_k=8)
        ref = ReferenceSolver().solve(quantize_input(inp))
        ref_ent = obsexplain.store().recent(1)[0]
        tpu = TPUSolver(relax_ladder=True)
        res = tpu.solve(inp)
        tpu_ent = obsexplain.store().recent(1)[0]
        assert res.placements == ref.placements
        assert tpu_ent["fingerprint"] == ref_ent["fingerprint"], (
            obsexplain.diff_records(ref_ent["record"], tpu_ent["record"])[:8]
        )

    def test_preemption_rides_the_record(self):
        # full node + no nodepool alternative: placing "hi" needs an eviction
        hi = [mkpod("hi", cpu="2", mem="2Gi", priority=100)]
        nodes = [mknode("n1", cpu="0", mem="0Mi",
                        victims=[victim("lo", priority=0), victim("lo2", priority=1)])]
        inp = SolverInput(pods=hi, nodes=nodes, nodepools=[], zones=ZONES)
        fps = {}
        for name, backend, q in (("oracle", ReferenceSolver(), True),
                                 ("native", NativeSolver(), False),
                                 ("tpu", TPUSolver(), False)):
            caw = sc.ClassAwareSolver(backend)
            _, ent = _capture_one(caw, inp, quantized=q)
            fps[name] = ent
        rec = fps["tpu"]["record"]
        assert rec["preemptions"], "eviction plan missing from the record"
        assert rec["preemptions"][0]["victim"] == "lo"
        assert rec["preemptions"][0]["for_pod"] == "hi"
        assert (fps["oracle"]["fingerprint"] == fps["native"]["fingerprint"]
                == fps["tpu"]["fingerprint"])

    def test_gang_verdicts_ride_the_record(self):
        committed = [mkpod(f"g{i}", cpu="500m", labels=gang_labels("job-a", 3))
                     for i in range(3)]
        doomed = [mkpod(f"d{i}", cpu="999", labels=gang_labels("job-b", 2))
                  for i in range(2)]
        inp = SolverInput(pods=committed + doomed, nodes=[],
                          nodepools=[pool()], zones=ZONES)
        fps = {}
        for name, backend, q in (("oracle", ReferenceSolver(), True),
                                 ("tpu", TPUSolver(), False)):
            caw = sc.ClassAwareSolver(backend)
            _, ent = _capture_one(caw, inp, quantized=q)
            fps[name] = ent
        rec = fps["tpu"]["record"]
        assert rec["gangs"]["job-a"]["committed"] is True
        assert rec["gangs"]["job-a"]["placed"] == 3
        assert rec["gangs"]["job-b"]["committed"] is False
        assert rec["gangs_unschedulable"] == ["job-b"]
        assert fps["oracle"]["fingerprint"] == fps["tpu"]["fingerprint"]

    def test_mesh_sharded_solve_host_derives_and_matches(self):
        rng = random.Random(3)
        pods = [
            mkpod(f"p{i:03d}", cpu=rng.choice(["250m", "500m", "1", "2"]),
                  mem=rng.choice(["512Mi", "1Gi", "2Gi"]))
            for i in range(60)
        ]
        inp = SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)
        obsexplain.configure(enabled=True, top_k=8)
        ReferenceSolver().solve(quantize_input(inp))
        ref_ent = obsexplain.store().recent(1)[0]
        s = TPUSolver(shards=2)
        s.solve(inp)
        ent = obsexplain.store().recent(1)[0]
        assert ent["fingerprint"] == ref_ent["fingerprint"], (
            obsexplain.diff_records(ref_ent["record"], ent["record"])[:8]
        )
        if s.stats.get("sharded_solves"):
            # sharded finish has no device table — the carve-out host-derives
            assert ent["annotations"]["source"] == "host"

    def test_resumed_solve_host_derives_and_matches(self):
        from tests.test_scan_resume import _add_replica, _fleet, _warm_solver

        inp = _fleet()
        tail = _add_replica(inp, 0, "tail-0")
        warm = _warm_solver()
        obsexplain.configure(enabled=True, top_k=8)
        warm.solve(inp)
        warm.solve(tail)
        assert warm.stats["resume_solves"] == 1, warm.stats
        ent = obsexplain.store().recent(1)[0]
        # resumed solves are stitched host-side: no device table
        assert ent["annotations"]["source"] == "host"
        ReferenceSolver().solve(quantize_input(tail))
        ref_ent = obsexplain.store().recent(1)[0]
        assert ent["fingerprint"] == ref_ent["fingerprint"], (
            obsexplain.diff_records(ref_ent["record"], ent["record"])[:8]
        )


# ---------------------------------------------------------------------------
# Off-path inertness
# ---------------------------------------------------------------------------


class TestOffPathInertness:
    def test_disabled_capture_returns_none_and_stores_nothing(self):
        obsexplain.configure(enabled=False)
        assert obsexplain.capture(None, None, "test") is None
        obsexplain.note("gang", {"gang": "g"})
        assert len(obsexplain.store()) == 0

    def test_disabled_hooks_allocate_nothing(self):
        obsexplain.configure(enabled=False)
        for _ in range(64):  # warm inline caches
            obsexplain.capture(None, None, "test")
            obsexplain.note("k", {})
        gc.collect()
        gc.disable()
        try:
            b0 = sys.getallocatedblocks()
            for _ in range(5_000):
                obsexplain.capture(None, None, "test")
                obsexplain.note("k", {})
            grew = sys.getallocatedblocks() - b0
        finally:
            gc.enable()
        assert grew < 50, f"disabled hooks allocated {grew} blocks"

    def test_explain_off_moves_zero_extra_d2h_bytes(self):
        pods = [mkpod(f"p{i}", cpu="500m") for i in range(12)]
        inp = SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)
        s = TPUSolver()
        s.solve(inp)  # cold
        led = s.ledger

        def delta():
            f0 = led.snapshot()["total"]["d2h_bytes"]
            s.solve(inp)
            return led.snapshot()["total"]["d2h_bytes"] - f0

        off1, off2 = delta(), delta()
        assert off1 == off2, "explain-off warm solves must fetch identically"
        obsexplain.configure(enabled=True, top_k=4)
        on = delta()
        obsexplain.configure(enabled=False)
        off3 = delta()
        assert off3 == off1, "disabling explain must restore the baseline"
        assert on > off1, "explain-on must move the EXPLAIN section"


# ---------------------------------------------------------------------------
# ExplainStore semantics
# ---------------------------------------------------------------------------


class TestStoreSemantics:
    def _solve_entry(self):
        pods = [mkpod(f"p{i}", cpu="500m") for i in range(4)]
        inp = SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)
        obsexplain.configure(enabled=True, top_k=4)
        res = ReferenceSolver().solve(quantize_input(inp))
        return res

    def test_capture_defers_and_reads_materialize(self):
        self._solve_entry()
        st = obsexplain.store()
        with st._lock:
            raw = next(iter(st._entries.values()))
        assert "_defer" in raw and "record" not in raw, (
            "capture must not build the record on the solve path"
        )
        ent = st.recent(1)[0]
        assert "record" in ent and "_defer" not in ent
        fp1 = ent["fingerprint"]
        assert st.recent(1)[0]["fingerprint"] == fp1  # idempotent

    def test_merge_put_unions_annotations(self):
        st = obsexplain.ExplainStore(ring=4)
        st.put("s1", {"solve_id": "s1", "record": {"pods": {}},
                      "annotations": {"source": "device", "rungs": 2}})
        out = st.put("s1", {"solve_id": "s1", "record": {"pods": {"p": {}}},
                            "annotations": {"source": "host"}})
        assert out["annotations"] == {"source": "host", "rungs": 2}
        assert out["record"]["pods"] == {"p": {}}
        assert len(st) == 1

    def test_ring_evicts_oldest(self):
        st = obsexplain.ExplainStore(ring=2)
        for i in range(4):
            st.put(f"s{i}", {"solve_id": f"s{i}", "record": {"pods": {}},
                             "annotations": {}})
        assert len(st) == 2
        assert st.get("s0") is None and st.get("s1") is None
        assert st.get("s3") is not None

    def test_by_pod_finds_the_solve(self):
        self._solve_entry()
        hits = obsexplain.store().by_pod("p2")
        assert hits and "p2" in hits[-1]["record"]["pods"]
        assert obsexplain.store().by_pod("nope") == []


# ---------------------------------------------------------------------------
# SLO engine
# ---------------------------------------------------------------------------


class TestSLOEngine:
    def test_parse_objectives(self):
        obj = obsslo.parse_objectives("solve=250:0.999,backend.dispatch=100:0.99")
        assert obj["solve"] == (0.25, 0.999)
        assert obj["backend.dispatch"] == (0.1, 0.99)
        assert obsslo.parse_objectives("") == obsslo.DEFAULT_OBJECTIVES
        for bad in ("solve=abc:0.9", "solve=100", "solve=100:1.5", "=1:0.9"):
            with pytest.raises(ValueError):
                obsslo.parse_objectives(bad)

    def test_burn_rates_with_injected_clock(self):
        t = [1000.0]
        obsslo.configure(objectives={"solve": (1.0, 0.99)},
                         clock=lambda: t[0])
        # 50% breach rate over the fast window: burn = 0.5 / 0.01 = 50
        for i in range(100):
            obsslo.record("solve", 2.0 if i % 2 == 0 else 0.1)
            t[0] += 1.0
        r = obsslo.burn_rates()["solve"]
        assert r["fast"] == pytest.approx(50.0, rel=0.1)
        assert obsslo.health()["state"] == "page"
        assert obsslo.health()["stages"]["solve"]["state"] == "page"

    def test_windows_age_out(self):
        t = [5000.0]
        obsslo.configure(objectives={"solve": (1.0, 0.99)},
                         clock=lambda: t[0])
        for _ in range(10):
            obsslo.record("solve", 5.0)  # all breaching
        assert obsslo.burn_rates()["solve"]["fast"] > 0
        t[0] += obsslo.SLOW_WINDOW_S + 60  # a full slow window later
        r = obsslo.burn_rates()["solve"]
        assert r["fast"] == 0.0 and r["slow"] == 0.0
        assert obsslo.health()["state"] == "ok"

    def test_unknown_stage_is_ignored(self):
        obsslo.configure(objectives={"solve": (1.0, 0.99)})
        obsslo.record("no.such.stage", 99.0)  # must not raise or register
        assert "no.such.stage" not in obsslo.burn_rates()

    def test_observe_trace_feeds_slo_and_meters(self):
        class _Span:
            def __init__(self, name, t0, t1):
                self.name, self.t0, self.t1 = name, t0, t1

        class _Trace:
            tenant_id = "acme"
            spans = [_Span("solve", 0.0, 2.0),
                     _Span("backend.dispatch", 0.0, 0.75),
                     _Span("open", 0.0, None)]

        obsslo.configure(objectives={"solve": (1.0, 0.99),
                                     "backend.dispatch": (0.5, 0.99)})
        solves0 = TENANT_METER_SOLVES.value(tenant="acme")
        obsslo.observe_trace(_Trace())
        assert TENANT_METER_SOLVES.value(tenant="acme") == solves0 + 1
        assert obsslo.burn_rates()["solve"]["fast"] > 0

    def test_meter_bytes_defaults_tenant(self):
        d0 = TENANT_METER_D2H_BYTES.value(tenant="default")
        obsslo.meter_bytes(None, d2h=1024)
        assert TENANT_METER_D2H_BYTES.value(tenant="default") == d0 + 1024


# ---------------------------------------------------------------------------
# Operator surface: /debug/explain, /debug/trace filters, /healthz slo
# ---------------------------------------------------------------------------


def _get(port, path):
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10
        ) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


@pytest.fixture(scope="class")
def server():
    from karpenter_tpu.operator.__main__ import serve_endpoints

    srv = serve_endpoints(0, 0, enable_profiling=False)
    yield srv.server_address[1]
    srv.shutdown()


class TestEndpoints:
    def test_explain_bad_params_400(self, server):
        for q in ("?solve_id=", "?pod="):
            status, body = _get(server, f"/debug/explain{q}")
            assert status == 400, (q, body)

    def test_explain_unknown_solve_404(self, server):
        status, body = _get(server, "/debug/explain?solve_id=nope")
        assert status == 404 and "unknown" in body

    def test_explain_serves_records_and_pod_lookup(self, server):
        pods = [mkpod(f"web-{i}", cpu="500m") for i in range(3)]
        inp = SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)
        obsexplain.configure(enabled=True, top_k=4)
        ReferenceSolver().solve(quantize_input(inp))
        sid = obsexplain.store().recent(1)[0]["solve_id"]

        status, body = _get(server, "/debug/explain")
        doc = json.loads(body)
        assert status == 200 and doc["enabled"] is True
        assert any(e["solve_id"] == sid for e in doc["result"])

        status, body = _get(server, f"/debug/explain?solve_id={sid}")
        doc = json.loads(body)
        assert status == 200
        assert "web-1" in doc["result"]["record"]["pods"]

        status, body = _get(server, "/debug/explain?pod=web-2")
        doc = json.loads(body)
        assert status == 200 and doc["result"], "pod lookup came up empty"

    def test_trace_filter_bad_params_400(self, server):
        for q in ("?solve_id=", "?tenant=", "?last=bogus"):
            status, body = _get(server, f"/debug/trace{q}")
            assert status == 400, (q, body)

    def test_trace_filters_narrow_the_dump(self, server):
        from karpenter_tpu.obs import trace as obstrace

        obstrace.configure(enabled=True, ring=16)
        try:
            tr = obstrace.begin("solve")
            obstrace.set_tenant(tr, "acme")
            with obstrace.attached(tr):
                with obstrace.span("solve"):
                    pass
            obstrace.finish(tr)
            sid = tr.solve_id
            status, body = _get(server, f"/debug/trace?solve_id={sid}")
            doc = json.loads(body)
            assert status == 200
            names = {e["args"].get("solve_id") for e in doc["traceEvents"]
                     if e.get("ph") == "X"}
            assert names == {sid}
            status, body = _get(server, "/debug/trace?tenant=acme")
            assert status == 200 and json.loads(body)["traceEvents"]
            status, body = _get(server, "/debug/trace?tenant=nobody")
            assert status == 200 and not json.loads(body)["traceEvents"]
        finally:
            obstrace.configure(enabled=False)

    def test_healthz_reports_slo_state(self, server):
        status, body = _get(server, "/healthz")
        doc = json.loads(body)
        assert status == 200
        assert doc["slo"]["state"] in ("ok", "warn", "page")
        assert "stages" in doc["slo"]


# ---------------------------------------------------------------------------
# Flight-recorder dump pruning (--flight-recorder-keep)
# ---------------------------------------------------------------------------


class TestRecorderPruning:
    def test_dumps_pruned_to_keep_oldest_first(self, tmp_path):
        from karpenter_tpu.obs.recorder import FlightRecorder

        rec = FlightRecorder(dir=str(tmp_path), keep=3)
        for i in range(7):
            old = tmp_path / f"karpenter-flightrec-000-fake{i}.json"
            old.write_text("{}")
            # stagger mtimes so oldest-first is deterministic
            import os
            os.utime(old, (1000 + i, 1000 + i))
        rec.dump("test")
        left = sorted(tmp_path.glob("karpenter-flightrec-*.json"))
        assert len(left) == 3, left
        names = [p.name for p in left]
        # the newest fakes + the real dump survive; fake0..fake4 pruned
        assert not any(f"fake{i}" in n for i in range(4) for n in names)

    def test_prune_survives_hostile_directory(self, tmp_path):
        from karpenter_tpu.obs.recorder import FlightRecorder

        rec = FlightRecorder(dir=str(tmp_path), keep=1)
        (tmp_path / "karpenter-flightrec-not-a-dump.json").mkdir()
        rec.dump("test")  # must not raise despite the undeletable entry

    def test_keep_floor_is_one(self, tmp_path):
        from karpenter_tpu.obs.recorder import FlightRecorder

        rec = FlightRecorder(dir=str(tmp_path), keep=0)
        assert rec.keep == 1

    def test_dump_attaches_recent_explain_records(self, tmp_path):
        from karpenter_tpu.obs.recorder import FlightRecorder

        pods = [mkpod("p0", cpu="500m")]
        inp = SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)
        obsexplain.configure(enabled=True, top_k=4)
        ReferenceSolver().solve(quantize_input(inp))
        rec = FlightRecorder(dir=str(tmp_path), keep=4)
        path = rec.dump("test")
        doc = json.loads((tmp_path / path.split("/")[-1]).read_text())
        assert doc["explain"], "dump must attach the recent explain records"
        assert "p0" in doc["explain"][-1]["record"]["pods"]
