"""Scheduling classes: priority, preemption, and gang scheduling (ISSUE 9).

Covers the solver/scheduling_class.py subsystem end to end:

- canonical ordering (priority-major, gang-contiguous) and its exact
  off-path inertness (flat batches / knobs off delegate verbatim),
- bit-identical three-legged planner parity (python oracle vs numpy host
  mirror vs jitted device kernels) on randomized tensors,
- atomic gang semantics (all-or-nothing rollback, min-ranks partial
  commit, claim-budget decline, malformed labels degrade to singletons),
- preemption semantics (strictly-lower-priority victims, minimal prefix
  ascending (priority, uid), evictable gating, counted declines),
- full-stack 3-way decision parity on randomized mixed-priority + gang
  fleets with preemption contention, including TPU path variants
  (relax ladder / suffix resume / mesh sharding on|off),
- operator knobs and startup validation,
- kwok e2e: gang surge converges with no gang partially placed, and a
  planned preemption executes through the controller into pod evictions,
- fleet failover soak: a gang trace through SolverFleet with a mid-trace
  wedge drops no solves and never lands a partial gang.
"""

import random

import numpy as np
import pytest

from karpenter_tpu.api import wellknown as wk
from karpenter_tpu.api.objects import (
    NodeClaimTemplate,
    NodePool,
    ObjectMeta,
    Pod,
)
from karpenter_tpu.catalog.catalog import CatalogSpec, generate
from karpenter_tpu.controllers import store as st
from karpenter_tpu.metrics.registry import SOLVER_PRIORITY_INVERSIONS
from karpenter_tpu.operator import options as opts
from karpenter_tpu.operator.operator import new_kwok_operator
from karpenter_tpu.provisioning.scheduler import (
    BoundPodRef,
    Eviction,
    ExistingNode,
    NodePoolSpec,
    SolverInput,
    ffd_key,
    ffd_sort,
)
from karpenter_tpu.scheduling.requirements import IN, Requirement, Requirements
from karpenter_tpu.solver import scheduling_class as sc
from karpenter_tpu.solver.backend import ReferenceSolver, TPUSolver, concrete_backend
from karpenter_tpu.solver.encode import quantize_input
from karpenter_tpu.solver.native import NativeSolver
from karpenter_tpu.utils.resources import PODS, Resources

CATALOG = generate(CatalogSpec())
ZONES = ("zone-1a", "zone-1b", "zone-1c")


@pytest.fixture(autouse=True)
def _class_knobs():
    """Every test starts and ends with the default-on knobs."""
    sc.configure(preemption=True, gang=True)
    yield
    sc.configure(preemption=True, gang=True)


def pool(name="default", weight=0, types=None, limits=None):
    return NodePoolSpec(
        name=name, weight=weight,
        requirements=Requirements.of(Requirement.create(wk.NODEPOOL_LABEL, IN, [name])),
        taints=[], instance_types=types if types is not None else CATALOG,
        limits=limits or Resources(),
    )


def mkpod(name, cpu="1", mem="1Gi", labels=None, priority=0, **kw):
    return Pod(
        meta=ObjectMeta(name=name, uid=name, labels=labels or {}),
        requests=Resources.parse({"cpu": cpu, "memory": mem}),
        priority=priority,
        **kw,
    )


def gang_labels(gid, size, min_ranks=None, topology=None):
    labels = {wk.GANG_LABEL: gid, wk.GANG_SIZE_LABEL: str(size)}
    if min_ranks is not None:
        labels[wk.GANG_MIN_RANKS_LABEL] = str(min_ranks)
    if topology is not None:
        labels[wk.GANG_TOPOLOGY_LABEL] = topology
    return labels


def victim(uid, priority=0, cpu="1", mem="1Gi", evictable=True):
    return BoundPodRef(
        uid=uid, priority=priority,
        requests=Resources.parse({"cpu": cpu, "memory": mem}),
        evictable=evictable,
    )


def mknode(name, cpu="2", mem="4Gi", victims=(), zone="zone-1a", schedulable=True):
    free = Resources.parse({"cpu": cpu, "memory": mem})
    free[PODS] = 100
    return ExistingNode(
        id=name,
        labels={wk.ZONE_LABEL: zone, wk.HOSTNAME_LABEL: name},
        taints=[], free=free, schedulable=schedulable,
        bound_pods=list(victims),
    )


# ---------------------------------------------------------------------------
# Ordering: priority-major, gang-contiguous, flat == pre-class
# ---------------------------------------------------------------------------


class TestOrdering:
    def test_priority_major(self):
        lo = [mkpod(f"lo{i}", cpu="4", priority=0) for i in range(3)]
        hi = [mkpod(f"hi{i}", cpu="1", priority=100) for i in range(3)]
        out = ffd_sort(lo + hi)
        # every high-priority pod precedes every low one, despite smaller size
        assert [p.meta.uid for p in out[:3]] == ["hi0", "hi1", "hi2"]
        assert all(p.priority == 0 for p in out[3:])

    def test_gang_contiguous_after_singletons(self):
        g = [mkpod(f"g{i}", cpu="1", labels=gang_labels("job-a", 3)) for i in range(3)]
        s = [mkpod(f"s{i}", cpu="2") for i in range(2)]
        out = [p.meta.uid for p in ffd_sort(g + s)]
        # same priority level: non-gang pods rank first (gang rank 0 = ""),
        # then the gang runs contiguously
        assert out == ["s0", "s1", "g0", "g1", "g2"]

    def test_flat_batch_is_pre_class_order(self):
        random.seed(3)
        pods = [
            mkpod(f"p{i:02d}", cpu=f"{random.choice([100, 500, 1000, 2000])}m",
                  mem=f"{random.choice([128, 512, 1024])}Mi")
            for i in range(25)
        ]
        out = ffd_sort(pods)
        assert [p.meta.uid for p in out] == [
            p.meta.uid for p in sorted(pods, key=ffd_key)
        ]

    def test_knobs_off_restore_flat_order(self):
        pods = [mkpod("a", cpu="1", priority=0), mkpod("b", cpu="4", priority=100),
                mkpod("c", cpu="2", labels=gang_labels("g", 1), priority=0)]
        sc.configure(preemption=False, gang=False)
        out = [p.meta.uid for p in ffd_sort(pods)]
        assert out == [p.meta.uid for p in sorted(pods, key=ffd_key)]


# ---------------------------------------------------------------------------
# Off-path inertness
# ---------------------------------------------------------------------------


class TestInertness:
    def _flat_input(self):
        pods = [mkpod(f"p{i}", cpu="500m") for i in range(8)]
        return SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)

    def test_flat_fleet_delegates_verbatim(self):
        inp = quantize_input(self._flat_input())
        caw = sc.ClassAwareSolver(ReferenceSolver())
        got = caw.solve(inp)
        want = ReferenceSolver().solve(inp)
        assert caw.class_stats["class_solves"] == 0
        assert got.placements == want.placements
        assert got.errors == want.errors
        assert got.evictions == [] and got.gangs_unschedulable == []

    def test_priorities_without_victims_stay_inert(self):
        # priority-diverse pending pods but no evictable bound pod below the
        # top priority: ordering engages, the passes do not
        pods = [mkpod("hi", priority=100), mkpod("lo", priority=0)]
        inp = SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)
        caw = sc.ClassAwareSolver(ReferenceSolver())
        caw.solve(quantize_input(inp))
        assert caw.class_stats["class_solves"] == 0

    def test_knobs_off_inert_with_classes_present(self):
        sc.configure(preemption=False, gang=False)
        pods = [mkpod("hi", priority=100),
                mkpod("g0", labels=gang_labels("job", 2)),
                mkpod("g1", labels=gang_labels("job", 2))]
        nodes = [mknode("n0", cpu="0", mem="0Mi", victims=[victim("v0", 0)])]
        inp = quantize_input(
            SolverInput(pods=pods, nodes=nodes, nodepools=[pool()], zones=ZONES)
        )
        caw = sc.ClassAwareSolver(ReferenceSolver())
        got = caw.solve(inp)
        want = ReferenceSolver().solve(inp)
        assert caw.class_stats["class_solves"] == 0
        assert got.placements == want.placements
        assert got.errors == want.errors
        assert got.evictions == []

    def test_tpu_flat_delegation_bit_identical(self):
        inp = self._flat_input()
        caw = sc.ClassAwareSolver(TPUSolver())
        got = caw.solve(inp)
        want = TPUSolver().solve(inp)
        assert got.placements == want.placements
        assert set(got.errors) == set(want.errors)
        # wrapper attribute discipline: the concrete backend's stats dict is
        # still readable through the chain (tests/bench depend on it)
        assert caw.stats is caw.inner.stats
        assert caw.stats["device_solves"] >= 1


# ---------------------------------------------------------------------------
# Planner parity: oracle vs host vs device, bit-identical
# ---------------------------------------------------------------------------


class TestPlannerParity:
    def test_select_planner(self):
        assert sc.select_planner(ReferenceSolver()) == "oracle"
        assert sc.select_planner(NativeSolver()) == "host"
        assert sc.select_planner(TPUSolver()) == "device"
        # through a wrapper chain, the concrete backend decides
        assert sc.select_planner(sc.ClassAwareSolver(TPUSolver())) == "device"
        assert type(concrete_backend(sc.ClassAwareSolver(NativeSolver()))).__name__ == "NativeSolver"

    def test_gang_commit_three_legs_randomized(self):
        rng = random.Random(90)
        for trial in range(25):
            ng = rng.randint(1, 5)
            s = rng.randint(1, 30)
            run_placed = [rng.randint(0, 1) for _ in range(s)]
            run_gang = [rng.randint(-1, ng - 1) for _ in range(s)]
            gang_size = [rng.randint(1, 6) for _ in range(ng)]
            gang_min_ranks = [rng.randint(0, gang_size[i]) for i in range(ng)]
            legs = {
                name: fns[0](run_placed, run_gang, gang_size, gang_min_ranks)
                for name, fns in sc.PLANNERS.items()
            }
            ref_commit, ref_placed = legs["oracle"]
            for name, (commit, placed) in legs.items():
                assert np.array_equal(np.asarray(commit), np.asarray(ref_commit)), (
                    f"trial {trial}: {name} commit diverges"
                )
                assert np.array_equal(np.asarray(placed), np.asarray(ref_placed)), (
                    f"trial {trial}: {name} placed diverges"
                )

    def test_preemption_plan_three_legs_randomized(self):
        rng = random.Random(91)
        for trial in range(40):
            E = rng.randint(1, 6)
            Vm = rng.randint(1, 5)
            R = rng.randint(1, 3)
            node_free = [[rng.randint(0, 5) for _ in range(R)] for _ in range(E)]
            victim_prio = [[rng.randint(0, 5) for _ in range(Vm)] for _ in range(E)]
            victim_req = [[[rng.randint(0, 3) for _ in range(R)] for _ in range(Vm)]
                          for _ in range(E)]
            victim_ok = [[rng.random() < 0.7 for _ in range(Vm)] for _ in range(E)]
            node_ok = [rng.random() < 0.8 for _ in range(E)]
            need = [rng.randint(1, 6) for _ in range(R)]
            pod_prio = rng.randint(0, 6)
            legs = {
                name: fns[1](node_free, victim_prio, victim_req, victim_ok,
                             node_ok, need, pod_prio)
                for name, fns in sc.PLANNERS.items()
            }
            ref_e, ref_mask = legs["oracle"]
            for name, (e, mask) in legs.items():
                assert int(e) == int(ref_e), (
                    f"trial {trial}: {name} node {e} != oracle {ref_e}"
                )
                assert np.array_equal(np.asarray(mask), np.asarray(ref_mask)), (
                    f"trial {trial}: {name} mask diverges"
                )

    def test_preemption_plan_free_fit_needs_no_eviction(self):
        for name, (_gc, plan) in sc.PLANNERS.items():
            e, mask = plan([[5, 5]], [[0]], [[[1, 1]]], [[True]], [True], [2, 2], 9)
            assert int(e) == 0 and not np.asarray(mask).any(), name

    def test_preemption_plan_no_eligible_node(self):
        for name, (_gc, plan) in sc.PLANNERS.items():
            e, mask = plan([[0, 0]], [[0]], [[[1, 1]]], [[True]], [False], [2, 2], 9)
            assert int(e) == -1 and not np.asarray(mask).any(), name


# ---------------------------------------------------------------------------
# Gang atomicity (orchestrator over the python oracle)
# ---------------------------------------------------------------------------


class TestGangAtomicity:
    def test_gang_fits_all_members_placed(self):
        pods = [mkpod(f"g{i}", cpu="500m", labels=gang_labels("job", 4)) for i in range(4)]
        caw = sc.ClassAwareSolver(ReferenceSolver())
        res = caw.solve(quantize_input(
            SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)
        ))
        assert all(f"g{i}" in res.placements for i in range(4))
        assert res.gangs_unschedulable == []
        assert caw.class_stats["gangs_placed"] == 1

    def test_gang_rollback_strips_every_member(self):
        # node fits 2 of 3 members; min_ranks defaults to size -> rollback,
        # and the freed slots go to the lower-priority singleton
        node = mknode("n0", cpu="2", mem="4Gi")
        pods = [mkpod(f"g{i}", cpu="1", labels=gang_labels("job", 3), priority=50)
                for i in range(3)]
        pods.append(mkpod("single", cpu="1", priority=0))
        caw = sc.ClassAwareSolver(ReferenceSolver())
        res = caw.solve(quantize_input(
            SolverInput(pods=pods, nodes=[node], nodepools=[], zones=ZONES)
        ))
        assert res.gangs_unschedulable == ["job"]
        assert not any(f"g{i}" in res.placements for i in range(3))
        for i in range(3):
            assert "unschedulable" in res.errors[f"g{i}"]
        assert res.placements["single"] == ("node", "n0")
        assert caw.class_stats["gangs_unschedulable"] == 1
        assert caw.class_stats["gang_rounds"] == 1

    def test_min_ranks_partial_commit(self):
        node = mknode("n0", cpu="2", mem="4Gi")
        pods = [mkpod(f"g{i}", cpu="1", labels=gang_labels("job", 3, min_ranks=2))
                for i in range(3)]
        caw = sc.ClassAwareSolver(ReferenceSolver())
        res = caw.solve(quantize_input(
            SolverInput(pods=pods, nodes=[node], nodepools=[], zones=ZONES)
        ))
        # two members reach min_ranks: the gang commits, the third pod keeps
        # its ordinary capacity error
        assert res.gangs_unschedulable == []
        placed = [i for i in range(3) if f"g{i}" in res.placements]
        assert len(placed) == 2
        assert caw.class_stats["gangs_placed"] == 1

    def test_oversized_gang_declines_and_strips(self, monkeypatch):
        monkeypatch.setattr(sc, "GANG_CLAIM_BUDGET", 2)
        pods = [mkpod(f"g{i}", cpu="100m", labels=gang_labels("big", 3)) for i in range(3)]
        pods.append(mkpod("single", cpu="100m"))
        caw = sc.ClassAwareSolver(ReferenceSolver())
        res = caw.solve(quantize_input(
            SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)
        ))
        assert res.gangs_unschedulable == ["big"]
        # all-or-nothing holds even for the up-front decline: no member of
        # the declined gang may keep a placement
        assert not any(f"g{i}" in res.placements for i in range(3))
        assert "single" in res.placements
        assert caw.class_stats["declines"] == 1

    def test_malformed_gang_labels_void_gang(self):
        labels = {wk.GANG_LABEL: "job", wk.GANG_SIZE_LABEL: "banana"}
        p = mkpod("p", labels=labels)
        assert p.gang() is None
        caw = sc.ClassAwareSolver(ReferenceSolver())
        res = caw.solve(quantize_input(
            SolverInput(pods=[p], nodes=[], nodepools=[pool()], zones=ZONES)
        ))
        # voided gang == flat batch: the wrapper never engages
        assert caw.class_stats["class_solves"] == 0
        assert "p" in res.placements


# ---------------------------------------------------------------------------
# Preemption semantics (orchestrator over the python oracle)
# ---------------------------------------------------------------------------


class TestPreemptionSemantics:
    def test_minimal_prefix_lowest_priority_first(self):
        node = mknode("n0", cpu="0", mem="0Mi", victims=[
            victim("v-c", priority=3), victim("v-a", priority=1), victim("v-b", priority=2),
        ])
        p = mkpod("hi", cpu="2", mem="2Gi", priority=100)
        caw = sc.ClassAwareSolver(ReferenceSolver())
        res = caw.solve(quantize_input(
            SolverInput(pods=[p], nodes=[node], nodepools=[], zones=ZONES)
        ))
        # two 1-cpu victims cover the 2-cpu need: the two LOWEST priorities
        # evict, the third survives; the pending pod waits for the next
        # reconcile (never placed in the same solve)
        assert [(e.pod_uid, e.victim_priority) for e in res.evictions] == [
            ("v-a", 1), ("v-b", 2),
        ]
        assert all(e.node_id == "n0" and e.for_pod == "hi" for e in res.evictions)
        assert "hi" not in res.placements and "hi" in res.errors
        assert caw.class_stats["preemptions"] == 2

    def test_equal_priority_never_engages(self):
        node = mknode("n0", cpu="0", mem="0Mi", victims=[victim("v", priority=100)])
        p = mkpod("hi", cpu="1", priority=100)
        caw = sc.ClassAwareSolver(ReferenceSolver())
        res = caw.solve(quantize_input(
            SolverInput(pods=[p], nodes=[node], nodepools=[], zones=ZONES)
        ))
        assert caw.class_stats["class_solves"] == 0
        assert res.evictions == []

    def test_insufficient_eligible_victims_plan_nothing(self):
        # one strictly-lower victim is not enough for the 2-cpu need; the
        # equal-priority one is ineligible -> no partial eviction plan
        node = mknode("n0", cpu="0", mem="0Mi", victims=[
            victim("v-lo", priority=1), victim("v-eq", priority=100),
        ])
        p = mkpod("hi", cpu="2", mem="2Gi", priority=100)
        caw = sc.ClassAwareSolver(ReferenceSolver())
        res = caw.solve(quantize_input(
            SolverInput(pods=[p], nodes=[node], nodepools=[], zones=ZONES)
        ))
        assert caw.class_stats["class_solves"] == 1
        assert res.evictions == []

    def test_unevictable_victims_are_skipped(self):
        n0 = mknode("n0", cpu="0", mem="0Mi",
                    victims=[victim("v-pinned", priority=0, evictable=False)])
        n1 = mknode("n1", cpu="0", mem="0Mi", victims=[victim("v-free", priority=0)])
        p = mkpod("hi", cpu="1", priority=100)
        caw = sc.ClassAwareSolver(ReferenceSolver())
        res = caw.solve(quantize_input(
            SolverInput(pods=[p], nodes=[n0, n1], nodepools=[], zones=ZONES)
        ))
        assert [e.pod_uid for e in res.evictions] == ["v-free"]
        assert res.evictions[0].node_id == "n1"

    def test_topology_interaction_declines_counted(self):
        # a gang topology label injects a preferred affinity term, which the
        # preemption pass treats as an active topology engine -> whole-pass
        # decline (counted), zero evictions
        node = mknode("n0", cpu="2", mem="4Gi", victims=[victim("v", priority=0)])
        pods = [
            mkpod(f"g{i}", cpu="1", priority=100,
                  labels=gang_labels("job", 2, topology=wk.ZONE_LABEL))
            for i in range(2)
        ]
        # the gang commits (fits in free); this lower-priority singleton is
        # the preemption candidate, but the gang's injected affinity terms
        # make the whole pass decline
        pods.append(mkpod("hi", cpu="1", priority=50))
        caw = sc.ClassAwareSolver(ReferenceSolver())
        res = caw.solve(quantize_input(
            SolverInput(pods=pods, nodes=[node], nodepools=[], zones=ZONES)
        ))
        assert res.evictions == []
        assert caw.class_stats["declines"] >= 1

    def test_eviction_budget_declines_counted(self, monkeypatch):
        monkeypatch.setattr(sc, "MAX_EVICTIONS_PER_SOLVE", 0)
        node = mknode("n0", cpu="0", mem="0Mi", victims=[victim("v", priority=0)])
        p = mkpod("hi", cpu="1", priority=100)
        caw = sc.ClassAwareSolver(ReferenceSolver())
        res = caw.solve(quantize_input(
            SolverInput(pods=[p], nodes=[node], nodepools=[], zones=ZONES)
        ))
        assert res.evictions == []
        assert caw.class_stats["declines"] == 1

    def test_free_tables_charged_with_own_placements(self):
        # two 1-cpu high-priority pods, node with 1 cpu free and one victim:
        # the first pod consumes the free cpu IN THIS SOLVE, so the second
        # must plan an eviction — without post-solve charging both would see
        # the same free capacity and nobody would preempt
        node = mknode("n0", cpu="1", mem="2Gi", victims=[victim("v", priority=0)])
        pods = [mkpod("hi-a", cpu="1", priority=100), mkpod("hi-b", cpu="1", priority=100)]
        caw = sc.ClassAwareSolver(ReferenceSolver())
        res = caw.solve(quantize_input(
            SolverInput(pods=pods, nodes=[node], nodepools=[], zones=ZONES)
        ))
        assert len(res.evictions) == 1
        assert res.evictions[0].pod_uid == "v"


# ---------------------------------------------------------------------------
# Full-stack 3-way parity: oracle / host / device, class passes engaged
# ---------------------------------------------------------------------------


def _claims_sig(res):
    return [
        (c.nodepool, sorted(c.instance_type_names), list(c.pod_uids))
        for c in res.claims
    ]


def assert_class_parity(inp: SolverInput):
    """Decision-identical results from the class wrapper over all three
    backends, plus the zero-priority-inversions acceptance gate."""
    inv0 = SOLVER_PRIORITY_INVERSIONS.value()
    legs = {
        "oracle": sc.ClassAwareSolver(ReferenceSolver()).solve(quantize_input(inp)),
        "host": sc.ClassAwareSolver(NativeSolver()).solve(inp),
        "device": sc.ClassAwareSolver(TPUSolver()).solve(inp),
    }
    ref = legs["oracle"]
    for name, got in legs.items():
        assert got.placements == ref.placements, f"{name}: placements diverge"
        assert set(got.errors) == set(ref.errors), f"{name}: errors diverge"
        assert _claims_sig(got) == _claims_sig(ref), f"{name}: claims diverge"
        assert got.evictions == ref.evictions, f"{name}: evictions diverge"
        assert got.gangs_unschedulable == ref.gangs_unschedulable, (
            f"{name}: gang verdicts diverge"
        )
    assert SOLVER_PRIORITY_INVERSIONS.value() == inv0, "priority inversion detected"
    return ref


def _random_fleet(seed: int) -> SolverInput:
    rng = random.Random(seed)
    nodes = []
    for e in range(rng.randint(2, 4)):
        victims = [
            victim(f"v-{e}-{v}", priority=rng.choice([0, 5]),
                   cpu=rng.choice(["500m", "1"]), mem=rng.choice(["512Mi", "1Gi"]),
                   evictable=rng.random() < 0.8)
            for v in range(rng.randint(0, 4))
        ]
        nodes.append(mknode(
            f"n{e}", cpu=str(rng.choice([0, 1, 2])), mem=rng.choice(["1Gi", "4Gi"]),
            victims=victims, zone=rng.choice(ZONES),
        ))
    pods = []
    for i in range(rng.randint(5, 12)):
        pods.append(mkpod(
            f"p{i:02d}", cpu=rng.choice(["250m", "500m", "1", "2"]),
            mem=rng.choice(["256Mi", "512Mi", "1Gi"]),
            priority=rng.choice([0, 10, 100]),
        ))
    for g in range(rng.randint(0, 3)):
        size = rng.randint(2, 4)
        min_ranks = size if rng.random() < 0.5 else rng.randint(1, size)
        for r in range(size):
            pods.append(mkpod(
                f"gang{g}-{r}", cpu=rng.choice(["500m", "1"]), mem="512Mi",
                labels=gang_labels(f"job-{g}", size, min_ranks=min_ranks),
                priority=rng.choice([50, 100]),
            ))
    nodepools = [pool()] if rng.random() < 0.5 else []
    return SolverInput(pods=pods, nodes=nodes, nodepools=nodepools, zones=ZONES)


class TestFullStackParity:
    def test_randomized_mixed_fleets(self):
        for seed in range(8):
            assert_class_parity(_random_fleet(seed))

    def test_preemption_contention_parity(self):
        nodes = [
            mknode(f"n{e}", cpu="0", mem="0Mi", victims=[
                victim(f"v-{e}-{v}", priority=v, cpu="1", mem="1Gi") for v in range(3)
            ])
            for e in range(3)
        ]
        pods = [mkpod(f"hi{i}", cpu="1", mem="1Gi", priority=100) for i in range(6)]
        res = assert_class_parity(
            SolverInput(pods=pods, nodes=nodes, nodepools=[], zones=ZONES)
        )
        assert res.evictions, "contention scenario must plan evictions"

    def test_gang_and_preemption_together_parity(self):
        nodes = [mknode(f"n{e}", cpu="2", mem="4Gi",
                        victims=[victim(f"v-{e}", priority=0, cpu="1")])
                 for e in range(2)]
        pods = [mkpod(f"g{r}", cpu="1", labels=gang_labels("job", 3), priority=50)
                for r in range(3)]
        pods += [mkpod(f"hi{i}", cpu="2", mem="2Gi", priority=100) for i in range(3)]
        assert_class_parity(
            SolverInput(pods=pods, nodes=nodes, nodepools=[], zones=ZONES)
        )

    def test_tpu_variants_decision_identical(self):
        inp = _random_fleet(42)
        ref = sc.ClassAwareSolver(ReferenceSolver()).solve(quantize_input(inp))
        variants = {
            "resume+ladder": TPUSolver(resume=True, relax_ladder=True),
            "no-resume,no-ladder": TPUSolver(resume=False, relax_ladder=False),
            "host-decode": TPUSolver(device_decode=False),
            "mesh-sharded": TPUSolver(shards=2),
        }
        for name, solver in variants.items():
            got = sc.ClassAwareSolver(solver).solve(inp)
            assert got.placements == ref.placements, name
            assert set(got.errors) == set(ref.errors), name
            assert got.evictions == ref.evictions, name
            assert got.gangs_unschedulable == ref.gangs_unschedulable, name


# ---------------------------------------------------------------------------
# Operator knobs + events
# ---------------------------------------------------------------------------


class TestOperatorKnobs:
    def test_defaults_on(self):
        o = opts.parse([])
        assert o.solver_preemption is True
        assert o.solver_gang is True

    def test_flags_off(self):
        o = opts.parse(["--solver-preemption", "false", "--solver-gang", "no"])
        assert o.solver_preemption is False
        assert o.solver_gang is False

    def test_env_typo_fails_closed(self, monkeypatch):
        monkeypatch.setenv("KARPENTER_SOLVER_PREEMPTION", "ture")
        with pytest.raises(SystemExit):
            opts.parse([])

    @staticmethod
    def _chain_types(solver):
        out, seen = [], set()
        while solver is not None and id(solver) not in seen:
            seen.add(id(solver))
            out.append(type(solver).__name__)
            d = getattr(solver, "__dict__", {})
            nxt = d.get("inner") or d.get("solver")
            solver = nxt if not isinstance(nxt, (str, bytes)) else None
        return out

    def test_operator_wires_class_wrapper_default_on(self):
        op = new_kwok_operator()
        assert "ClassAwareSolver" in self._chain_types(op.solver)
        assert op.preemption is not None and op.recorder is not None

    def test_operator_knobs_off_no_wrapper(self):
        op = new_kwok_operator(solver_preemption=False, solver_gang=False)
        assert "ClassAwareSolver" not in self._chain_types(op.solver)
        assert sc.PRIORITY_ENABLED is False and sc.GANG_ENABLED is False


class TestEvents:
    def test_preempted_event_shape(self):
        from karpenter_tpu.events import recorder as ev

        e = ev.preempted("victim", "node-1", "winner")
        assert (e.kind, e.type, e.reason) == ("pods", "Normal", "Preempted")
        assert "node-1" in e.message and "winner" in e.message

    def test_gang_unschedulable_event_shape(self):
        from karpenter_tpu.events import recorder as ev

        e = ev.gang_unschedulable("g0", "job-a")
        assert (e.kind, e.type, e.reason) == ("pods", "Warning", "GangUnschedulable")
        assert "job-a" in e.message


# ---------------------------------------------------------------------------
# kwok e2e: gang surge + executed preemption
# ---------------------------------------------------------------------------


class FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t

    def advance(self, s):
        self.t += s


def mkpool(name="default", limits=None):
    from karpenter_tpu.api.objects import Disruption

    return NodePool(
        meta=ObjectMeta(name=name),
        template=NodeClaimTemplate(),
        disruption=Disruption(consolidation_policy="WhenEmptyOrUnderutilized",
                              consolidate_after_s=0.0),
        limits=limits or Resources(),
    )


@pytest.fixture
def op():
    clock = FakeClock()
    o = new_kwok_operator(clock=clock)
    o.clock = clock
    return o


class TestKwokE2E:
    def test_gang_surge_converges_no_partial_gang(self, op):
        op.store.create(st.NODEPOOLS, mkpool())
        sizes = {}
        for g in range(3):
            gid = f"job-{g}"
            sizes[gid] = 3
            for r in range(3):
                op.store.create(st.PODS, mkpod(
                    f"{gid}-{r}", cpu="500m", mem="512Mi",
                    labels=gang_labels(gid, 3), priority=100,
                ))
        # a gang no instance type can host: must stay entirely unbound
        sizes["job-doomed"] = 2
        for r in range(2):
            op.store.create(st.PODS, mkpod(
                f"job-doomed-{r}", cpu="999", labels=gang_labels("job-doomed", 2),
                priority=100,
            ))
        op.manager.settle()
        pods = op.store.list(st.PODS)
        bound_by_gang = {}
        for p in pods:
            gid = p.meta.labels.get(wk.GANG_LABEL)
            if gid:
                bound_by_gang.setdefault(gid, []).append(p.node_name is not None)
        for gid, flags in bound_by_gang.items():
            n_bound = sum(flags)
            assert n_bound in (0, sizes[gid]), f"gang {gid} partially placed: {n_bound}"
        assert sum(bound_by_gang["job-doomed"]) == 0
        assert all(sum(bound_by_gang[f"job-{g}"]) == 3 for g in range(3))
        assert any(
            e.reason == "GangUnschedulable" for e in op.recorder.events()
        ), "doomed gang must surface an event"

    def test_preemption_executes_and_high_priority_lands(self, op):
        op.store.create(st.NODEPOOLS, mkpool())
        for i in range(4):
            op.store.create(st.PODS, mkpod(f"lo{i}", cpu="500m", mem="512Mi", priority=0))
        op.manager.settle()
        nodes = op.store.list(st.NODES)
        assert len(nodes) >= 1
        target = nodes[0].meta.name
        # remove the nodepool: existing capacity is now the ONLY option, so
        # the high-priority arrival must preempt to land
        op.store.delete(st.NODEPOOLS, "default")
        free = next(
            n for n in op.cluster.existing_nodes_for_scheduler() if n.id == target
        ).free
        fill_m = int(free.get_("cpu"))
        if fill_m > 0:
            op.store.create(st.PODS, mkpod("filler", cpu=f"{fill_m}m", mem="1Mi", priority=0))
            op.manager.settle()
        hi = mkpod("hi", cpu="1", mem="512Mi", priority=1000)
        op.store.create(st.PODS, hi)
        op.manager.settle()
        pods = {p.meta.uid: p for p in op.store.list(st.PODS)}
        assert pods["hi"].node_name == target, "high-priority pod must land on the node"
        assert op.preemption.executed >= 1
        assert any(e.reason == "Preempted" for e in op.recorder.events())


# ---------------------------------------------------------------------------
# Fleet failover soak with gangs in flight (chaos acceptance)
# ---------------------------------------------------------------------------


class TestFleetGangSoak:
    def test_gang_trace_survives_mid_trace_wedge(self):
        import bench
        from karpenter_tpu import faults
        from karpenter_tpu.solver.fleet import SolverFleet
        from karpenter_tpu.solver.pipeline import DISRUPTION

        soak_cls = bench._soak_solver_cls()

        def factory(i):
            return sc.ClassAwareSolver(soak_cls())

        inp = bench._gang_input(n_nodes=4, victims_per_node=2, n_high=6,
                                n_gangs=3, gang_size=3)
        gang_sizes = {"job-doomed": 3, **{f"job-{g:02d}": 3 for g in range(3)}}
        canary = bench.build_input(2)
        fleet = SolverFleet(
            solver_factory=factory, size=2,
            canary_input_fn=lambda: canary, canary_deadline_s=0.5,
            fence_after_misses=1, fence_drain_s=0.1, recovery_probe_s=3600.0,
        )
        plan = faults.FaultPlan(seed=9)
        wedge = None
        tickets = []
        failed = 0
        try:
            with faults.active(plan):
                for step in range(8):
                    if step == 3:
                        wedge = plan.wedge("solver.device_hang", tag="owner-0")
                    for _ in range(2):
                        tickets.append(fleet.submit(inp, kind=DISRUPTION))
                    fleet.probe_once()
                results = []
                for t in tickets:
                    try:
                        results.append(t.result(timeout=60))
                    except Exception:  # noqa: BLE001 — counted as dropped
                        failed += 1
            dropped = fleet.unresolved()
            stats = dict(fleet.stats)
        finally:
            if wedge is not None:
                wedge.release()
            fleet.close()
        assert failed + dropped == 0, "soak dropped solves"
        assert stats["failovers"] >= 1, "wedge must force a failover"
        # atomicity through failover: no result may carry a partial gang
        member_uids = {
            uid: p.meta.labels[wk.GANG_LABEL]
            for p in inp.pods for uid in [p.meta.uid]
            if wk.GANG_LABEL in p.meta.labels
        }
        for res in results:
            placed_per_gang = {}
            for uid in res.placements:
                gid = member_uids.get(uid)
                if gid:
                    placed_per_gang[gid] = placed_per_gang.get(gid, 0) + 1
            for gid, n in placed_per_gang.items():
                assert n == gang_sizes[gid], f"partial gang {gid}: {n}"
            assert sum(1 for u in res.placements if member_uids.get(u) == "job-doomed") == 0
