"""Tenancy-layer semantics: WFQ fairness, admission, failure isolation,
shared compile residency, fence-requeue order, decision parity.

The mux (solver/tenancy.py) multiplexes per-tenant solve streams onto one
shared owner pool; these tests pin its contract: under saturation dispatch
shares converge to the configured weights (start-time fair queueing, no
starvation); a tenant at its admission depth gets the typed reject and
nothing else changes; one tenant's poisoned inputs trip only THAT tenant's
breaker and degrade only that tenant to its own oracle (zero drops — the
victim's solves still land); tenants share the shape-keyed compile caches
(same padded shapes -> same kernels, compiles flat as tenants grow) while
arena residency stays namespaced; a fence mid-stream requeues every parked
request with per-tenant order preserved and zero drops; and the mux changes
no decisions (bit-identical to solving without it).
"""

import dataclasses
import threading
import time

import pytest

from karpenter_tpu import faults
from karpenter_tpu.metrics.registry import (
    TENANT_ADMISSION_REJECTS,
    TENANT_DEGRADED,
)
from karpenter_tpu.provisioning.scheduler import SolverInput
from karpenter_tpu.solver import encode_cache as ec
from karpenter_tpu.solver.backend import ReferenceSolver, TPUSolver
from karpenter_tpu.solver.fleet import SolverFleet
from karpenter_tpu.solver.pipeline import (
    DISRUPTION,
    PROVISIONING,
    ServiceStopped,
    SolveService,
    SolveTicket,
    Superseded,
)
from karpenter_tpu.solver.tenancy import (
    TenantAdmissionReject,
    TenantMux,
    TenantRegistry,
    TenantSpec,
)

from tests.test_batched_consolidation import ZONES, mkpod, pool


def mkinput(pod_name="a", cpu="250m"):
    return SolverInput(
        pods=[mkpod(pod_name, cpu=cpu)], nodes=[], nodepools=[pool()],
        zones=ZONES,
    )


def mkregistry(*specs):
    return TenantRegistry([TenantSpec(*s) if isinstance(s, tuple) else s
                           for s in specs])


class FakeService:
    """Downstream stand-in with the SolveService submit surface: records
    the forward order, optionally gates (so a test can build a full mux
    backlog before any dispatch) or fails marked inputs downstream."""

    def __init__(self, size=1, depth=1, gated=False, fail_marker=None,
                 fail_fn=False):
        self.size = size
        self.depth = depth
        self.gate = threading.Event()
        if not gated:
            self.gate.set()
        self.fail_marker = fail_marker
        self.fail_fn = fail_fn
        self.order = []  # (tenant_id, pod_name) in forward order
        self.stats = {"submitted": 0}

    def submit(self, inp, kind=PROVISIONING, rev=None, tenant_id=None):
        assert self.gate.wait(10)
        t = SolveTicket(kind, rev=rev, tenant_id=tenant_id)
        name = inp.pods[0].meta.name
        self.order.append((tenant_id, name))
        self.stats["submitted"] += 1
        if self.fail_marker is not None and self.fail_marker in name:
            t._deliver(error=RuntimeError(f"poisoned input {name}"))
        else:
            t._deliver(result=("solved", tenant_id, name))
        return t

    def submit_fn(self, fn, kind=DISRUPTION, tenant_id=None):
        assert self.gate.wait(10)
        t = SolveTicket(kind, tenant_id=tenant_id)
        self.order.append((tenant_id, "<fn>"))
        self.stats["submitted"] += 1
        if self.fail_fn:
            t._deliver(error=RuntimeError("fn dispatch failed"))
        else:
            t._deliver(result=("dispatched", tenant_id))
        return t

    def queue_depth(self):
        return 0

    def occupancy(self):
        return 0.0

    def close(self):
        self.gate.set()


# ------------------------------------------------------------------ registry


def test_registry_parse_weights_and_failures():
    reg = TenantRegistry.parse("a, b,c", "a=2,c=0.5", max_queue_depth=7)
    assert [(s.tenant_id, s.weight, s.max_queue_depth)
            for s in reg.tenants()] == [
        ("a", 2.0, 7), ("b", 1.0, 7), ("c", 0.5, 7),
    ]
    assert reg.first().tenant_id == "a"
    assert "b" in reg and "nope" not in reg
    with pytest.raises(ValueError):
        TenantRegistry.parse("")
    with pytest.raises(ValueError):
        TenantRegistry.parse("a,a")
    with pytest.raises(ValueError):
        TenantRegistry.parse("a", "b=2")  # weight for an unknown tenant
    with pytest.raises(ValueError):
        TenantRegistry.parse("a", "a=0")  # non-positive weight
    with pytest.raises(ValueError):
        TenantRegistry.parse("a", "a=x")  # non-numeric weight
    with pytest.raises(ValueError):
        TenantSpec("a", max_queue_depth=0)


# ----------------------------------------------------------------- WFQ / admission


def test_wfq_dispatch_shares_converge_to_weights():
    """Full backlog, one downstream slot: the dispatch order is the pure
    WFQ schedule. Weight 2:1 must yield a 2:1 interleave in every window —
    and the light tenant must never starve (the start-time-fair tag freeze:
    a backlogged tenant's tag does not inflate with the virtual clock)."""
    svc = FakeService(size=1, depth=1, gated=True)
    mux = TenantMux(svc, mkregistry(("a", 2.0), ("b", 1.0)),
                    own_service=True)
    try:
        # primer: occupies the single slot while the backlog builds
        tickets = [mux.submit(mkinput("a-primer"), tenant_id="a",
                              kind=DISRUPTION)]
        time.sleep(0.05)  # let the dispatcher park in the gated forward
        for i in range(24):
            tickets.append(mux.submit(mkinput(f"a-{i}"), tenant_id="a",
                                      kind=DISRUPTION))
        for i in range(12):
            tickets.append(mux.submit(mkinput(f"b-{i}"), tenant_id="b",
                                      kind=DISRUPTION))
        svc.gate.set()
        for t in tickets:
            assert t.result(timeout=10)
        order = [tid for tid, _ in svc.order][1:]  # drop the primer
        assert len(order) == 36
        # every 3-dispatch window carries 2 a's and 1 b (±1 for the seam)
        for k in range(1, 13):
            prefix = order[: 3 * k]
            assert abs(prefix.count("a") - 2 * k) <= 1, (k, order)
            assert abs(prefix.count("b") - k) <= 1, (k, order)
        # per-tenant FIFO through the mux
        a_seq = [n for tid, n in svc.order if tid == "a" and "primer" not in n]
        assert a_seq == [f"a-{i}" for i in range(24)]
        b_seq = [n for tid, n in svc.order if tid == "b"]
        assert b_seq == [f"b-{i}" for i in range(12)]
        assert mux.unresolved() == 0
    finally:
        mux.close()


def test_admission_reject_is_typed_and_isolated():
    """At max_queue_depth open requests, submit raises the typed reject,
    counts it, and enqueues nothing; the OTHER tenant is unaffected."""
    svc = FakeService(gated=True)
    mux = TenantMux(svc, mkregistry(TenantSpec("a", max_queue_depth=2),
                                    TenantSpec("b", max_queue_depth=2)),
                    own_service=True)
    rejects0 = TENANT_ADMISSION_REJECTS.value(tenant="a")
    try:
        t1 = mux.submit(mkinput("a-0"), tenant_id="a", kind=DISRUPTION)
        t2 = mux.submit(mkinput("a-1"), tenant_id="a", kind=DISRUPTION)
        with pytest.raises(TenantAdmissionReject) as ei:
            mux.submit(mkinput("a-2"), tenant_id="a", kind=DISRUPTION)
        assert ei.value.tenant_id == "a"
        assert ei.value.depth == 2 and ei.value.limit == 2
        assert TENANT_ADMISSION_REJECTS.value(tenant="a") == rejects0 + 1
        # b is nowhere near ITS limit: admission is per-tenant state
        tb = mux.submit(mkinput("b-0"), tenant_id="b", kind=DISRUPTION)
        svc.gate.set()
        for t in (t1, t2, tb):
            assert t.result(timeout=10)
        assert mux.tenant_stats()["a"]["rejected"] == 1
        assert mux.tenant_stats()["b"]["rejected"] == 0
        # depth freed after completion: a admits again
        assert mux.submit(mkinput("a-3"), tenant_id="a",
                          kind=DISRUPTION).result(timeout=10)
    finally:
        mux.close()


def test_unknown_tenant_refused():
    svc = FakeService()
    mux = TenantMux(svc, mkregistry(("a", 1.0)), own_service=True)
    try:
        with pytest.raises(KeyError):
            mux.submit(mkinput("x"), tenant_id="ghost")
        with pytest.raises(KeyError):
            mux.view("ghost")
    finally:
        mux.close()


def test_mux_coalescing_is_same_tenant_only():
    """Queued provisioning snapshots coalesce newest-wins WITHIN a tenant;
    another tenant's queued snapshot must survive."""
    svc = FakeService(gated=True)
    mux = TenantMux(svc, mkregistry(("a", 1.0), ("b", 1.0)),
                    own_service=True)
    try:
        primer = mux.submit(mkinput("primer"), tenant_id="a",
                            kind=DISRUPTION)
        time.sleep(0.05)  # primer holds the slot; the rest queue at the mux
        ta1 = mux.submit(mkinput("a-old"), tenant_id="a", kind=PROVISIONING)
        tb = mux.submit(mkinput("b-keep"), tenant_id="b", kind=PROVISIONING)
        ta2 = mux.submit(mkinput("a-new"), tenant_id="a", kind=PROVISIONING)
        assert ta1.done() and ta1.superseded()
        with pytest.raises(Superseded) as ei:
            ta1.result()
        assert ei.value.by is ta2  # maps to the MUX ticket, not a downstream one
        assert not tb.done()
        svc.gate.set()
        assert tb.result(timeout=10)
        assert ta2.result(timeout=10)
        assert primer.result(timeout=10)
        names = [n for _, n in svc.order]
        assert "b-keep" in names and "a-new" in names
        assert "a-old" not in names  # never forwarded
    finally:
        mux.close()


# ---------------------------------------------------------------- failure isolation


def test_breaker_isolation_poison_degrades_only_the_victim():
    """Tenant a's poisoned inputs fail downstream: a's breaker opens, a's
    solves replay on a's OWN oracle (still landing — zero drops), while b
    keeps solving on the same shared downstream with a closed breaker."""
    svc = FakeService(fail_marker="poison")
    mux = TenantMux(svc, mkregistry(("a", 1.0), ("b", 1.0)),
                    breaker_threshold=2, breaker_probe_s=60.0,
                    own_service=True)
    degraded0 = TENANT_DEGRADED.value(tenant="a")
    try:
        # two downstream failures open a's breaker (threshold=2); each
        # failed solve replays on a's oracle and still returns placements
        for i in range(2):
            res = mux.submit(mkinput(f"poison-{i}"), tenant_id="a",
                             kind=DISRUPTION).result(timeout=10)
            assert res.claims and res.claims[0].pod_uids == [f"poison-{i}"]
        deadline = time.monotonic() + 5
        while (mux.tenant_stats()["a"]["breaker"] != "open"
               and time.monotonic() < deadline):
            time.sleep(0.01)
        assert mux.tenant_stats()["a"]["breaker"] == "open"
        # a is now breaker-routed: solves go straight to a's oracle lane,
        # never touching the shared downstream
        fwd0 = len(svc.order)
        res = mux.submit(mkinput("a-degraded"), tenant_id="a",
                         kind=DISRUPTION).result(timeout=10)
        assert res.claims and res.claims[0].pod_uids == ["a-degraded"]
        assert len(svc.order) == fwd0  # nothing forwarded for a
        assert TENANT_DEGRADED.value(tenant="a") >= degraded0 + 3
        # b rides the SAME downstream, unaffected: closed breaker, no
        # degraded solves, forwarded normally
        resb = mux.submit(mkinput("b-fine"), tenant_id="b",
                          kind=DISRUPTION).result(timeout=10)
        assert resb == ("solved", "b", "b-fine")
        st = mux.tenant_stats()
        assert st["b"]["breaker"] == "closed"
        assert st["b"]["degraded"] == 0
        assert st["a"]["failed"] == 0  # every poisoned solve still landed
        assert mux.unresolved() == 0
    finally:
        mux.close()


def test_breaker_routed_submits_never_lose_the_lane_wakeup():
    """Regression: the WFQ scan routes breaker-open heads to the oracle lane
    from inside the dispatch wait loop; if that append doesn't notify, an
    idle lane thread that consumed submit()'s wakeup first (and re-waited on
    a then-empty lane) sleeps forever on a resolvable ticket. Hammer the
    breaker-routed path — every submit must land degraded, promptly."""
    svc = FakeService(fail_marker="poison")
    mux = TenantMux(svc, mkregistry(("a", 1.0)), breaker_threshold=1,
                    breaker_probe_s=600.0, own_service=True)
    try:
        assert mux.submit(mkinput("poison-0"), tenant_id="a",
                          kind=DISRUPTION).result(timeout=10)
        deadline = time.monotonic() + 5
        while (mux.tenant_stats()["a"]["breaker"] != "open"
               and time.monotonic() < deadline):
            time.sleep(0.01)
        assert mux.tenant_stats()["a"]["breaker"] == "open"
        fwd0 = len(svc.order)
        for i in range(50):
            res = mux.submit(mkinput(f"lane-{i}"), tenant_id="a",
                             kind=DISRUPTION).result(timeout=10)
            assert res.claims and res.claims[0].pod_uids == [f"lane-{i}"]
        assert len(svc.order) == fwd0  # all 50 rode the lane, none forwarded
        assert mux.unresolved() == 0
    finally:
        mux.close()


def test_fn_requests_bypass_breaker_and_surface_failures_verbatim():
    """Device-bound closures cannot replay on an oracle, so they bypass the
    tenant breaker (an open breaker still forwards them) and a downstream
    failure surfaces verbatim — while the SAME tenant's input solves keep
    landing degraded on its oracle."""
    svc = FakeService(fail_marker="poison", fail_fn=True)
    mux = TenantMux(svc, mkregistry(("a", 1.0)), breaker_threshold=1,
                    breaker_probe_s=60.0, own_service=True)
    try:
        assert mux.submit(mkinput("poison-0"), tenant_id="a",
                          kind=DISRUPTION).result(timeout=10)
        deadline = time.monotonic() + 5
        while (mux.tenant_stats()["a"]["breaker"] != "open"
               and time.monotonic() < deadline):
            time.sleep(0.01)
        assert mux.tenant_stats()["a"]["breaker"] == "open"
        fwd0 = len(svc.order)
        with pytest.raises(RuntimeError, match="fn dispatch failed"):
            mux.submit_fn(lambda: None, tenant_id="a",
                          kind=DISRUPTION).result(timeout=10)
        assert ("a", "<fn>") in svc.order[fwd0:]  # forwarded despite OPEN
        assert mux.submit(mkinput("a-inp"), tenant_id="a",
                          kind=DISRUPTION).result(timeout=10)
        assert mux.tenant_stats()["a"]["failed"] == 1  # only the closure
    finally:
        mux.close()


def test_close_resolves_every_ticket():
    svc = FakeService(gated=True)
    mux = TenantMux(svc, mkregistry(("a", 1.0), ("b", 1.0)),
                    own_service=True)
    held = [mux.submit(mkinput(f"q-{i}"), tenant_id=("a", "b")[i % 2],
                       kind=DISRUPTION) for i in range(6)]
    svc.gate.set()
    mux.close()
    for t in held:
        assert t.done()
        err = t.error()
        assert err is None or isinstance(err, (ServiceStopped, Superseded))
    assert mux.unresolved() == 0
    with pytest.raises(ServiceStopped):
        mux.submit(mkinput("late"), tenant_id="a")


# ------------------------------------------------------- shared compile residency


def _ffd_compile_count():
    import karpenter_tpu.solver.tpu.ffd as ffd

    total = 0
    for name in ("ffd_solve", "ffd_solve_ckpt", "ffd_resume",
                 "ffd_solve_ladder", "ffd_solve_sharded", "gang_commit",
                 "preemption_plan"):
        fn = getattr(ffd, name, None)
        size = getattr(fn, "_cache_size", None)
        if callable(size):
            try:
                total += size()
            except Exception:  # noqa: BLE001 — introspection-only helper
                continue
    return total


def test_tenants_share_compile_buckets_zero_extra_compiles():
    """The tenancy boundary: arena RESIDENCY and the encode core-cache are
    per-tenant namespaces, but compile buckets are shape-keyed and shared —
    8 tenants with the same padded shapes add ZERO kernel compiles."""
    from karpenter_tpu.solver import arena as arena_mod

    s = TPUSolver()
    base = mkinput("shared")
    r0 = s.solve(dataclasses.replace(base, tenant_id="t0"))
    assert r0.claims
    compiles0 = _ffd_compile_count()
    unpack0 = len(arena_mod._UNPACK_CACHE)
    buckets0 = len(s.arena._buckets)
    for i in range(1, 8):
        r = s.solve(dataclasses.replace(base, tenant_id=f"t{i}"))
        # decisions are tenant-independent: same input, same placements
        assert r.placements == r0.placements
        assert r.errors == r0.errors
    assert _ffd_compile_count() == compiles0  # zero extra kernel compiles
    assert len(arena_mod._UNPACK_CACHE) == unpack0  # shape-keyed, shared
    # ...while residency namespaced per tenant: tenants adopt DISTINCT
    # arena buckets for the SAME shapes (the bucket LRU may already have
    # evicted the earliest tenants — residency is bounded, compiles are not)
    ns = {k[2] for k in s.arena._buckets if len(k) > 2}
    assert len(ns) >= 2 and "t7" in ns
    # and each tenant got its own encode core-cache namespace
    assert {f"t{i}" for i in range(1, 8)} <= set(ec._TENANT_CORE_CACHES)


# -------------------------------------------------------------- fence / parity


class RecordingOracle(ReferenceSolver):
    """TaggedOracle idiom from test_solver_fleet: honours the wedge-class
    fault sites and records the served order (pod names reach the record
    only when the wedge is not holding the dispatch)."""

    def __init__(self):
        super().__init__()
        self.fault_tag = None
        self.seen = []

    def solve(self, inp):
        faults.check("solver.device_hang", tag=self.fault_tag)
        faults.check("solver.device_lost", tag=self.fault_tag)
        name = inp.pods[0].meta.name
        if "canary" not in name:
            self.seen.append(name)
        return super().solve(inp)


def mkmuxed_fleet(tenants, size=2, fence_after_misses=1, max_inflight=32):
    solvers = []

    def _factory(i):
        s = RecordingOracle()
        solvers.append(s)
        return s

    fleet = SolverFleet(
        _factory, size=size,
        canary_input_fn=lambda: mkinput("fleet-canary", cpu="100m"),
        canary_deadline_s=0.25, fence_after_misses=fence_after_misses,
        recovery_probe_s=60.0, fence_drain_s=0.1,
    )
    mux = TenantMux(fleet, mkregistry(*tenants), max_inflight=max_inflight,
                    own_service=True)
    return mux, fleet, solvers


def test_fence_mid_stream_requeues_in_per_tenant_order_zero_drops():
    """Wedge owner-0 while tenant streams are in flight: fencing requeues
    its parked work onto owner-1 with each tenant's relative order intact
    and EVERY ticket resolving with its own solve (no drop, no cross-wire,
    no tenant breaker tripped — an owner fence is not tenant poison)."""
    mux, fleet, solvers = mkmuxed_fleet([("a", 1.0), ("b", 1.0),
                                         ("c", 1.0)])
    plan = faults.FaultPlan(seed=3)
    wedge = plan.wedge("solver.device_hang", tag="owner-0")
    try:
        with faults.active(plan):
            tickets = {}
            for i in range(4):
                for tid in ("a", "b", "c"):
                    name = f"{tid}-{i}"
                    tickets[name] = mux.submit(
                        mkinput(name), tenant_id=tid, kind=DISRUPTION
                    )
            # wait for owner-0 to park in the wedge and owner-1 to drain
            # its share, so the fence genuinely happens MID-stream
            deadline = time.monotonic() + 10
            while wedge.wedged == 0 and time.monotonic() < deadline:
                time.sleep(0.01)
            assert wedge.wedged >= 1
            # disruption routes round-robin over the 2 owners, so owner-1's
            # share is exactly half; the other half is parked behind the
            # wedge and CANNOT complete until fenced + requeued
            while (sum(t.done() for t in tickets.values()) < 6
                   and time.monotonic() < deadline):
                time.sleep(0.01)
            assert sum(t.done() for t in tickets.values()) == 6
            pre_fence = list(solvers[1].seen)
            assert fleet.probe_once()["owner-0"] == "fenced"
            for name, t in tickets.items():
                res = t.result(timeout=15)
                assert res.claims and res.claims[0].pod_uids == [name]
        assert fleet.stats["requeued"] >= 1
        # the requeued block replays on owner-1 in per-tenant order
        requeued = solvers[1].seen[len(pre_fence):]
        for tid in ("a", "b", "c"):
            idx = [int(n.split("-")[1]) for n in requeued
                   if n.startswith(tid)]
            assert idx == sorted(idx), (tid, requeued)
        # an owner fence is infrastructure, not tenant fault: no breaker
        # opened, nothing degraded to a tenant oracle
        st = mux.tenant_stats()
        for tid in ("a", "b", "c"):
            assert st[tid]["breaker"] == "closed"
            assert st[tid]["degraded"] == 0
        assert mux.unresolved() == 0
    finally:
        wedge.release()
        mux.close()


def test_decision_parity_mux_vs_direct():
    """The mux changes scheduling, never decisions: a solve through
    mux -> pipeline is bit-identical to the bare backend's answer."""
    svc = SolveService(RecordingOracle())
    mux = TenantMux(svc, mkregistry(("a", 2.0), ("b", 1.0)),
                    own_service=True)
    try:
        for tid in ("a", "b"):
            direct = ReferenceSolver().solve(mkinput("par"))
            via = mux.submit(mkinput("par"), tenant_id=tid,
                             kind=DISRUPTION).result(timeout=10)
            assert via.placements == direct.placements
            assert via.errors == direct.errors
            assert len(via.claims) == len(direct.claims)
        # the SolveService surface the operator relies on delegates through
        assert isinstance(mux.stats, dict)
        assert mux.stats["tenants"] == 2
        assert mux.queue_depth() == 0
        assert 0.0 <= mux.occupancy() <= 1.0
        view = mux.view("b")
        res = view.submit(mkinput("via-view"), kind=DISRUPTION).result(
            timeout=10)
        assert res.claims
        assert view.tenant_stats()["b"]["completed"] >= 2
    finally:
        mux.close()
