"""Mesh-sharded FFD solve: decision identity with the one-device scan.

ISSUE 7 acceptance: partitioning the run axis across a device mesh
(TPUSolver(shards=N), solver/backend.py _sharded_solve_async) must be
BIT-IDENTICAL in decisions to the single-device scan — the carry-exchange
stitch either proves a block non-interacting and combines it additively, or
replays it sequentially from the true prefix carry; either way the result
is the sequential result. Covered here on the CPU virtual mesh (conftest
forces --xla_force_host_platform_device_count=8):

- randomized fleet parity across mesh sizes {1, 2, 4, 8}, fresh and with
  existing nodes;
- composition with the relax ladder (preference fleets) and with
  checkpointed suffix resume (append-tail re-solves hit the block-boundary
  carries);
- constrained fleets (V > 0 / Q > 0): the sparse constraint engine
  (ISSUE 20) extended the stitch with per-block touch-mask triggers, so
  these fleets SHARD — the old v_axis/q_axis declines no longer fire; the
  remaining decline class (tiny fleets, no mesh) counts with a {reason}
  label on karpenter_solver_sharded_fallback_total.
"""

import random

import pytest

from karpenter_tpu.api import wellknown as wk
from karpenter_tpu.api.objects import ObjectMeta, Pod, TopologySpreadConstraint
from karpenter_tpu.provisioning.scheduler import SolverInput
from karpenter_tpu.solver.backend import TPUSolver
from karpenter_tpu.utils.resources import Resources

from tests.test_zone_device import ZONES, mknode, mkpod, pool

MESH_SIZES = (1, 2, 4, 8)


def _mkpod(name, cpu, mem, **kw):
    return Pod(meta=ObjectMeta(name=name, uid=name),
               requests=Resources.parse({"cpu": cpu, "memory": mem}), **kw)


def _random_fleet(rng, n):
    """Mixed fleet: enough distinct signatures that the run axis splits
    across every mesh size, sizes spanning several instance types."""
    cpus = ["250m", "500m", "1", "1500m", "2", "3", "4", "6"]
    mems = ["512Mi", "1Gi", "2Gi", "4Gi", "8Gi"]
    return [
        _mkpod(f"p{i:03d}", rng.choice(cpus), rng.choice(mems))
        for i in range(n)
    ]


def _assert_same(a, b, tag=""):
    assert a.placements == b.placements, f"{tag}: placements diverge"
    assert set(a.errors) == set(b.errors), f"{tag}: errors diverge"
    assert len(a.claims) == len(b.claims), f"{tag}: claim count diverges"
    for i, (ca, cb) in enumerate(zip(a.claims, b.claims)):
        assert ca.pod_uids == cb.pod_uids, f"{tag}: claim {i} pods"
        assert ca.nodepool == cb.nodepool, f"{tag}: claim {i} pool"
        assert sorted(ca.instance_type_names) == sorted(
            cb.instance_type_names
        ), f"{tag}: claim {i} types"


class TestShardedParity:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_randomized_fleet_parity_across_mesh_sizes(self, seed):
        rng = random.Random(seed)
        pods = _random_fleet(rng, 90 + 10 * seed)
        inp = SolverInput(pods=pods, nodes=[], nodepools=[pool()],
                          zones=ZONES)
        base = TPUSolver().solve(inp)
        for n in MESH_SIZES:
            s = TPUSolver(shards=n)
            _assert_same(s.solve(inp), base, f"seed={seed} shards={n}")
            if n >= 2:
                # the mesh path must have actually served the solve — a
                # silent decline would make this parity proof vacuous
                assert s.stats["sharded_solves"] == 1, s.stats
                assert s.stats["sharded_fallbacks"] == 0, s.stats
            else:
                assert s.stats["sharded_solves"] == 0, s.stats

    def test_parity_with_existing_nodes(self):
        rng = random.Random(7)
        pods = _random_fleet(rng, 80)
        nodes = [mknode(f"n{i}", ZONES[i % 3]) for i in range(5)]
        inp = SolverInput(pods=pods, nodes=nodes, nodepools=[pool()],
                          zones=ZONES)
        base = TPUSolver().solve(inp)
        for n in (2, 8):
            s = TPUSolver(shards=n)
            _assert_same(s.solve(inp), base, f"nodes shards={n}")
            assert s.stats["sharded_solves"] == 1, s.stats

    def test_fixup_replay_fires_on_interacting_blocks(self):
        """One pool, many mutually-poured specs: later blocks' pods fit the
        prefix's open claims, so the stitch must REPLAY (not accept) — the
        fix-up counter proves the trigger logic saw the interaction."""
        pods = [_mkpod(f"p{i:03d}", f"{2000 - i * 20}m", "1Gi")
                for i in range(48)]
        inp = SolverInput(pods=pods, nodes=[], nodepools=[pool()],
                          zones=ZONES)
        base = TPUSolver().solve(inp)
        s = TPUSolver(shards=8)
        _assert_same(s.solve(inp), base, "fixup")
        assert s.stats["sharded_solves"] == 1, s.stats
        assert s.stats["shard_fixup_runs"] > 0, s.stats


class TestShardedComposition:
    def test_suffix_resume_composes_with_sharding(self):
        """Append-tail re-solve: the second solve resumes from a recorded
        block-boundary carry (the per-device checkpoint), replays only the
        changed tail blocks, and still matches the single-device scan."""
        pods = [_mkpod(f"p{i:03d}", f"{4000 - i * 50}m", "1Gi")
                for i in range(60)]
        inp1 = SolverInput(pods=pods, nodes=[], nodepools=[pool()],
                          zones=ZONES)
        # grow the LAST run's count only: same groups, same Sp bucket, so
        # the run-identity prefix covers 7 of 8 blocks
        pods2 = pods + [_mkpod(f"z{i}", f"{4000 - 59 * 50}m", "1Gi")
                        for i in range(3)]
        inp2 = SolverInput(pods=pods2, nodes=[], nodepools=[pool()],
                           zones=ZONES)
        s = TPUSolver(shards=8)
        _assert_same(s.solve(inp1), TPUSolver().solve(inp1), "resume warm")
        _assert_same(s.solve(inp2), TPUSolver().solve(inp2), "resume tail")
        assert s.stats["shard_resume_solves"] == 1, s.stats
        assert s.stats["shard_resume_runs_skipped"] > 0, s.stats

    def test_relax_fleet_parity_under_shards(self):
        """Respect-mode preference fleets: the relax loop's materialized
        solves route through the same sharded seam; zone-preference
        materializations carry V > 0 signatures and — since the sparse
        constraint lift — shard like any other fleet, deciding
        identically."""
        tsc = TopologySpreadConstraint(
            max_skew=1, topology_key=wk.ZONE_LABEL,
            label_selector={"app": "w"}, when_unsatisfiable="ScheduleAnyway",
        )
        pods = [mkpod(f"r{i:02d}", cpu="2", mem="4Gi", labels={"app": "w"},
                      topology_spread=[tsc]) for i in range(12)]
        inp = SolverInput(pods=pods, nodes=[], nodepools=[pool()],
                          zones=ZONES)
        base = TPUSolver().solve(inp)
        s = TPUSolver(shards=8)
        _assert_same(s.solve(inp), base, "relax")


class TestShardedConstrained:
    """The sparse-constraint lift: V > 0 / Q > 0 fleets SHARD. Before the
    sparse engine these declined up-front (the carry combine was treated as
    inexpressible); now the stitch's touch-mask triggers (conditions (e)
    touched-V-sig seed movement, (f) kind-2 prefix-claim coupling) replay
    exactly the interacting blocks and decisions stay bit-identical."""

    def _zone_fleet(self, n=24):
        tsc = TopologySpreadConstraint(
            max_skew=1, topology_key=wk.ZONE_LABEL,
            label_selector={"app": "w"},
        )
        return [mkpod(f"v{i:02d}", cpu="2", mem="4Gi",
                      labels={"app": "w"}, topology_spread=[tsc])
                for i in range(n)]

    def test_zone_spread_fleet_shards_after_sparse_lift(self):
        """Zone-spread fleet (V > 0): served BY the mesh path, zero
        fallbacks, identical decisions — the headline acceptance of the
        lift (no v_axis/q_axis declines remain)."""
        # filler signatures so the run axis splits across all 8 shards
        pods = self._zone_fleet(9) + _random_fleet(random.Random(3), 40)
        inp = SolverInput(pods=pods, nodes=[], nodepools=[pool()],
                          zones=ZONES)
        base = TPUSolver().solve(inp)
        s = TPUSolver(shards=8)
        _assert_same(s.solve(inp), base, "V-shard")
        assert s.stats["sharded_solves"] == 1, s.stats
        assert s.stats["sharded_fallbacks"] == 0, s.stats

    @pytest.mark.parametrize("n", MESH_SIZES)
    def test_constrained_parity_across_mesh_sizes(self, n):
        """Mixed TSC + affinity fleet: parity across every mesh size with
        the sparse engine gated on (auto) — the ISSUE 20 acceptance sweep."""
        from karpenter_tpu.api.objects import PodAffinityTerm

        anti = PodAffinityTerm(label_selector={"app": "solo"},
                               topology_key=wk.ZONE_LABEL, anti=True)
        pods = (
            self._zone_fleet(12)
            + [mkpod(f"a{i}", cpu="1", mem="2Gi", labels={"app": "solo"},
                     affinity_terms=[anti]) for i in range(5)]
            + _random_fleet(random.Random(17), 50)
        )
        nodes = [mknode(f"n{i}", ZONES[i % 3]) for i in range(4)]
        inp = SolverInput(pods=pods, nodes=nodes, nodepools=[pool()],
                          zones=ZONES)
        base = TPUSolver().solve(inp)
        s = TPUSolver(shards=n)
        _assert_same(s.solve(inp), base, f"constrained shards={n}")
        if n >= 2:
            assert s.stats["sharded_solves"] == 1, s.stats
            assert s.stats["sharded_fallbacks"] == 0, s.stats


class TestShardedFallback:

    def test_tiny_fleet_declines_below_mesh_width(self):
        """Fewer real runs than devices: nothing to partition — decline
        (counted) and solve single-device."""
        pods = [_mkpod(f"t{i}", "1", "1Gi") for i in range(6)]  # one run
        inp = SolverInput(pods=pods, nodes=[], nodepools=[pool()],
                          zones=ZONES)
        base = TPUSolver().solve(inp)
        s = TPUSolver(shards=8)
        _assert_same(s.solve(inp), base, "tiny")
        assert s.stats["sharded_fallbacks"] >= 1, s.stats
        assert s.stats["sharded_solves"] == 0, s.stats

    def test_shards_off_never_touches_the_mesh_path(self):
        s = TPUSolver()  # shards=0 default
        pods = _random_fleet(random.Random(11), 40)
        s.solve(SolverInput(pods=pods, nodes=[], nodepools=[pool()],
                            zones=ZONES))
        assert s.stats["sharded_solves"] == 0
        assert s.stats["sharded_fallbacks"] == 0
        assert s._shard_mesh() is None
