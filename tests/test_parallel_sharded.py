"""Multi-device sharding correctness (SURVEY.md §2.10 TPU-equivalent row).

The disruption engine's scale axis is independent candidate solves; sharding
that batch axis across a `jax.sharding.Mesh` must not change any decision.
conftest.py forces an 8-device virtual CPU mesh, so these tests exercise the
same sharded program `dryrun_multichip` compiles — per-shard results must be
bit-identical to the unsharded sequential kernel.
"""

import numpy as np
import pytest

import jax

from karpenter_tpu.api import wellknown as wk
from karpenter_tpu.api.objects import ObjectMeta, Pod
from karpenter_tpu.catalog.catalog import CatalogSpec, generate
from karpenter_tpu.parallel.sharded import batched_solve, make_mesh, replicate_args
from karpenter_tpu.provisioning.scheduler import NodePoolSpec, SolverInput
from karpenter_tpu.scheduling.requirements import IN, Requirement, Requirements
from karpenter_tpu.solver.backend import TPUSolver, kernel_args
from karpenter_tpu.solver.encode import encode, quantize_input
from karpenter_tpu.solver.tpu.ffd import ffd_solve
from karpenter_tpu.utils.resources import Resources

CATALOG = generate(CatalogSpec())
ZONES = ("zone-1a", "zone-1b", "zone-1c")
N_DEV = 8


def _scenario(num_pods=40):
    pool = NodePoolSpec(
        name="default",
        weight=0,
        requirements=Requirements.of(
            Requirement.create(wk.NODEPOOL_LABEL, IN, ["default"])
        ),
        taints=[],
        instance_types=CATALOG,
    )
    sizes = [("250m", "512Mi"), ("500m", "1Gi"), ("1", "2Gi"), ("2", "4Gi")]
    pods = []
    for i in range(num_pods):
        cpu, mem = sizes[i % len(sizes)]
        pods.append(
            Pod(
                meta=ObjectMeta(name=f"p{i:04d}", uid=f"p{i:04d}"),
                requests=Resources.parse({"cpu": cpu, "memory": mem}),
            )
        )
    inp = SolverInput(pods=pods, nodes=[], nodepools=[pool], zones=ZONES)
    enc = encode(quantize_input(inp))
    solver = TPUSolver(max_claims=64)
    args, _dims = kernel_args(enc, solver._bucket)
    return args


def test_mesh_has_eight_devices():
    assert len(jax.devices()) >= N_DEV, jax.devices()
    mesh = make_mesh(N_DEV)
    assert mesh.devices.size == N_DEV


def test_sharded_replicated_batch_matches_sequential():
    """Identical rows sharded across 8 devices == one unsharded solve."""
    args = _scenario(40)
    seq = ffd_solve(*args, max_claims=64)

    mesh = make_mesh(N_DEV)
    batched = replicate_args(args, N_DEV)
    out = batched_solve(mesh, batched, max_claims=64)

    used = np.asarray(out.state.used)
    assert used.shape == (N_DEV,)
    assert (used == int(seq.state.used)).all()
    for b in range(N_DEV):
        np.testing.assert_array_equal(np.asarray(out.take_e)[b], np.asarray(seq.take_e))
        np.testing.assert_array_equal(np.asarray(out.take_c)[b], np.asarray(seq.take_c))
        np.testing.assert_array_equal(np.asarray(out.leftover)[b], np.asarray(seq.leftover))
        np.testing.assert_array_equal(
            np.asarray(out.state.c_mask)[b], np.asarray(seq.state.c_mask)
        )


def test_sharded_heterogeneous_batch_matches_per_row_sequential():
    """Each shard solves a DIFFERENT subset (run counts zeroed per row —
    exactly the consolidation evaluator's batching); every row must equal
    the sequential solve of that row's inputs."""
    args = _scenario(40)
    run_count = np.asarray(args[1])
    S = run_count.shape[0]

    rng = np.random.RandomState(7)
    batched = list(replicate_args(args, N_DEV))
    b_counts = np.broadcast_to(run_count, (N_DEV, S)).copy()
    for b in range(1, N_DEV):
        mask = rng.rand(S) < 0.5
        b_counts[b] = np.where(mask, run_count, 0)
    batched[1] = b_counts

    mesh = make_mesh(N_DEV)
    out = batched_solve(mesh, tuple(batched), max_claims=64)

    for b in range(N_DEV):
        row_args = list(args)
        row_args[1] = b_counts[b]
        seq = ffd_solve(*row_args, max_claims=64)
        assert int(np.asarray(out.state.used)[b]) == int(seq.state.used), f"row {b}"
        np.testing.assert_array_equal(
            np.asarray(out.take_c)[b], np.asarray(seq.take_c), err_msg=f"row {b}"
        )
        np.testing.assert_array_equal(
            np.asarray(out.leftover)[b], np.asarray(seq.leftover), err_msg=f"row {b}"
        )
        np.testing.assert_array_equal(
            np.asarray(out.state.c_cum)[b], np.asarray(seq.state.c_cum), err_msg=f"row {b}"
        )


class TestTwoLevelMesh:
    """Multi-host shape: a (dcn, ici) 2-level mesh for the candidate axis —
    validated on the virtual 8-device CPU mesh as 2 hosts x 4 chips. The
    batch axis shards over both levels; results must be bit-identical to
    the flat single-mesh dispatch (the solve has no cross-candidate
    communication, so the hierarchy only changes WHERE shards live)."""

    def test_two_level_verdicts_bit_identical(self):
        import jax
        import numpy as np
        from jax.sharding import NamedSharding, PartitionSpec

        from karpenter_tpu.solver.tpu import consolidate as cons

        if len(jax.devices()) < 8:
            import pytest

            pytest.skip("needs the 8-device virtual mesh")
        mesh2 = cons.make_candidate_mesh(jax.devices()[:8], hosts=2)
        assert mesh2.axis_names == ("dcn", "ici")
        assert mesh2.devices.shape == (2, 4)
        # drive the live evaluator twice: once with the process-default
        # mesh, once with the 2-level mesh forced
        import __graft_entry__ as ge

        n1 = ge._dryrun_live_consolidation(8)
        old_mesh, old_init = cons._MESH, cons._MESH_INIT
        try:
            cons._MESH, cons._MESH_INIT = mesh2, True
            cons._sharded_ffd.cache_clear()
            n2 = ge._dryrun_live_consolidation(8)
        finally:
            cons._MESH, cons._MESH_INIT = old_mesh, old_init
            cons._sharded_ffd.cache_clear()
        assert n1 == n2


def test_sharded_mixed_axis_scenario_matches_sequential():
    """Round-5 mixed zone+ct solves under the SHARDED dispatch: the
    concatenated-domain kernel (extra D=Z+C columns, col_axis/group_daxis/
    node_dom2 args) must shard over the candidate mesh bit-identically to
    the unsharded kernel — the consolidation evaluator batches mixed-axis
    universes through this exact program."""
    from karpenter_tpu.api.objects import TopologySpreadConstraint

    pool = NodePoolSpec(
        name="default",
        weight=0,
        requirements=Requirements.of(
            Requirement.create(wk.NODEPOOL_LABEL, IN, ["default"])
        ),
        taints=[],
        instance_types=CATALOG,
    )
    pods = []
    for i in range(24):
        p = Pod(
            meta=ObjectMeta(name=f"z{i:03d}", uid=f"z{i:03d}",
                            labels={"app": "w"}),
            requests=Resources.parse({"cpu": "1", "memory": "2Gi"}),
        )
        p.topology_spread = [TopologySpreadConstraint(
            max_skew=1, topology_key=wk.ZONE_LABEL, label_selector={"app": "w"})]
        pods.append(p)
    for i in range(8):
        p = Pod(
            meta=ObjectMeta(name=f"c{i:03d}", uid=f"c{i:03d}",
                            labels={"tier": "ct"}),
            requests=Resources.parse({"cpu": "500m", "memory": "1Gi"}),
        )
        p.topology_spread = [TopologySpreadConstraint(
            max_skew=1, topology_key=wk.CAPACITY_TYPE_LABEL,
            label_selector={"tier": "ct"})]
        pods.append(p)
    inp = SolverInput(pods=pods, nodes=[], nodepools=[pool], zones=ZONES)
    enc = encode(quantize_input(inp))
    assert enc.v_axis == "mixed"
    solver = TPUSolver(max_claims=64)
    args, _dims = kernel_args(enc, solver._bucket)

    seq = ffd_solve(*args, max_claims=64)
    mesh = make_mesh(N_DEV)
    out = batched_solve(mesh, replicate_args(args, N_DEV), max_claims=64)
    used = np.asarray(out.state.used)
    assert (used == int(seq.state.used)).all()
    assert int(np.asarray(seq.leftover).sum()) == 0
    for b in range(N_DEV):
        np.testing.assert_array_equal(
            np.asarray(out.take_c)[b], np.asarray(seq.take_c))
        np.testing.assert_array_equal(
            np.asarray(out.state.c_zc_bits)[b], np.asarray(seq.state.c_zc_bits))


class TestProcessMesh:
    """Process-spanning mesh construction (ISSUE 18): single-process
    degenerates to the legacy mesh; multi-process validation is fail-closed
    (MeshConstructionError, never a silently-wrong mesh); the shard_map
    fallback is decision-identical to the plain per-row program."""

    def test_single_process_degenerates_to_make_mesh(self):
        from karpenter_tpu.parallel.sharded import make_process_mesh

        mesh, (lo, hi) = make_process_mesh(4)
        assert mesh.devices.size == 4
        assert (lo, hi) == (0, 4)  # one process owns the whole grid

    def test_uneven_shard_split_fails_closed(self, monkeypatch):
        from karpenter_tpu.parallel.sharded import (
            MeshConstructionError,
            make_process_mesh,
        )

        monkeypatch.setattr(jax, "process_count", lambda: 2)
        with pytest.raises(MeshConstructionError,
                           match="not a multiple of process_count=2"):
            make_process_mesh(3)

    def test_oversubscribed_processes_fail_closed(self, monkeypatch):
        from karpenter_tpu.parallel.sharded import (
            MeshConstructionError,
            make_process_mesh,
        )

        monkeypatch.setattr(jax, "process_count", lambda: 2)
        # 32 shards over 2 processes needs 16 devices per process; the
        # virtual mesh holds 8 — must refuse, not build a straddling mesh
        with pytest.raises(MeshConstructionError,
                           match="devices per process but processes hold"):
            make_process_mesh(32)

    def test_one_sided_shardings_fail_closed(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        from karpenter_tpu.parallel.sharded import (
            MeshConstructionError,
            mesh_sharded_call,
        )

        mesh = make_mesh(4)
        sh = NamedSharding(mesh, P(mesh.axis_names[0]))
        with pytest.raises(MeshConstructionError, match="one-sided"):
            mesh_sharded_call(mesh, lambda x: x, in_shardings=sh)
        with pytest.raises(MeshConstructionError, match="one-sided"):
            mesh_sharded_call(mesh, lambda x: x, out_shardings=sh)

    def test_shard_map_fallback_matches_plain_fn(self):
        from karpenter_tpu.parallel.sharded import mesh_sharded_call

        mesh = make_mesh(4, axis="shards")
        x = np.arange(8 * 3, dtype=np.float32).reshape(8, 3)
        fn = lambda a: a * 2.0 + 1.0  # noqa: E731 — per-shard body
        out = mesh_sharded_call(mesh, fn)(x)
        np.testing.assert_array_equal(np.asarray(out), fn(x))

    def test_explicit_shardings_match_plain_fn(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        from karpenter_tpu.parallel.sharded import mesh_sharded_call

        mesh = make_mesh(4, axis="shards")
        sh = NamedSharding(mesh, P("shards", None))
        x = np.arange(8 * 3, dtype=np.float32).reshape(8, 3)
        fn = lambda a: a * 3.0 - 2.0  # noqa: E731
        out = mesh_sharded_call(mesh, fn, in_shardings=sh, out_shardings=sh)(x)
        np.testing.assert_array_equal(np.asarray(out), fn(x))

    def test_put_process_sharded_roundtrip(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        from karpenter_tpu.parallel.sharded import (
            make_process_mesh,
            put_process_sharded,
        )

        mesh, (lo, hi) = make_process_mesh(4)
        arr = np.arange(4 * 5, dtype=np.int32).reshape(4, 5)
        dev = put_process_sharded(mesh, arr, lo, hi)
        np.testing.assert_array_equal(np.asarray(dev), arr)
        want = NamedSharding(mesh, P(mesh.axis_names[0], None))
        assert dev.sharding.is_equivalent_to(want, arr.ndim)
