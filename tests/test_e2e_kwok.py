"""End-to-end control loop on the kwok fake cloud.

The hermetic equivalent of the reference's test strategy ring 1 + kwok
(SURVEY.md §4): the REAL provisioner/lifecycle/termination/disruption
controllers run against the in-memory cloud, driving pods through
pending -> NodeClaim -> fabricated Node -> registration -> binding, and
nodes through drain -> instance termination, without any cluster.
"""

import pytest

from karpenter_tpu.api import wellknown as wk
from karpenter_tpu.api.objects import (
    Budget,
    Disruption,
    NodeClaimTemplate,
    NodePool,
    ObjectMeta,
    Pod,
    PodDisruptionBudget,
)
from karpenter_tpu.controllers import store as st
from karpenter_tpu.operator.operator import new_kwok_operator
from karpenter_tpu.utils.resources import Resources


class FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t

    def advance(self, s):
        self.t += s


def mkpool(name="default", weight=0, limits=None, consolidation="WhenEmptyOrUnderutilized"):
    return NodePool(
        meta=ObjectMeta(name=name),
        template=NodeClaimTemplate(),
        disruption=Disruption(consolidation_policy=consolidation, consolidate_after_s=0.0),
        limits=limits or Resources(),
        weight=weight,
    )


def mkpod(name, cpu="1", mem="1Gi", labels=None, **kw):
    return Pod(
        meta=ObjectMeta(name=name, uid=name, labels=labels or {}),
        requests=Resources.parse({"cpu": cpu, "memory": mem}),
        **kw,
    )


@pytest.fixture
def op():
    clock = FakeClock()
    o = new_kwok_operator(clock=clock)
    o.clock = clock
    return o


class TestProvisioningE2E:
    def test_pending_pod_to_running_node(self, op):
        op.store.create(st.NODEPOOLS, mkpool())
        for i in range(5):
            op.store.create(st.PODS, mkpod(f"p{i}", cpu="500m"))
        op.manager.settle()
        nodes = op.store.list(st.NODES)
        claims = op.store.list(st.NODECLAIMS)
        pods = op.store.list(st.PODS)
        assert len(claims) == 1
        assert len(nodes) == 1
        assert nodes[0].ready
        assert all(p.node_name == nodes[0].meta.name for p in pods)
        assert claims[0].launched and claims[0].registered and claims[0].initialized
        assert claims[0].instance_type == nodes[0].meta.labels[wk.INSTANCE_TYPE_LABEL]

    def test_no_nodepool_no_nodes(self, op):
        op.store.create(st.PODS, mkpod("p"))
        op.manager.settle()
        assert not op.store.list(st.NODES)
        assert not op.store.list(st.NODECLAIMS)

    def test_incompatible_pods_two_nodes(self, op):
        op.store.create(st.NODEPOOLS, mkpool())
        op.store.create(st.PODS, mkpod("a", node_selector={wk.ARCH_LABEL: "amd64"}))
        op.store.create(st.PODS, mkpod("b", node_selector={wk.ARCH_LABEL: "arm64"}))
        op.manager.settle()
        nodes = op.store.list(st.NODES)
        assert len(nodes) == 2
        archs = {n.meta.labels[wk.ARCH_LABEL] for n in nodes}
        assert archs == {"amd64", "arm64"}

    def test_second_wave_reuses_capacity(self, op):
        op.store.create(st.NODEPOOLS, mkpool())
        op.store.create(st.PODS, mkpod("p0", cpu="500m", mem="512Mi"))
        op.manager.settle()
        nodes1 = {n.meta.name for n in op.store.list(st.NODES)}
        # a second small pod fits the free capacity of the existing node
        op.store.create(st.PODS, mkpod("p1", cpu="100m", mem="128Mi"))
        op.manager.settle()
        nodes2 = {n.meta.name for n in op.store.list(st.NODES)}
        assert nodes1 == nodes2
        assert op.store.get(st.PODS, "p1").node_name in nodes2

    def test_ice_retry_lands_elsewhere(self, op):
        op.store.create(st.NODEPOOLS, mkpool())
        # exhaust capacity for the cheapest offerings of every m5a/m6g family
        # in one zone; launch must walk up the price list
        for it in list(op.cloud.types.values()):
            for o in it.offerings:
                if o.zone == "zone-1a" and o.capacity_type == "spot":
                    op.cloud.set_capacity(it.name, o.zone, o.capacity_type, 0)
        op.store.create(st.PODS, mkpod("p"))
        op.manager.settle()
        nodes = op.store.list(st.NODES)
        assert len(nodes) == 1  # still provisioned (other offerings)

    def test_nodepool_limits_cap_capacity(self, op):
        # limits are checked BEFORE each claim creation (a single claim may
        # overshoot — reference semantics); pods forced onto separate claims
        # via distinct zone selectors show the cap
        # smallest surviving type for a 1-cpu pod is 2-cpu (m5.large class),
        # so each claim charges 2 cpu; limit 4 admits two claims, blocks the third
        op.store.create(st.NODEPOOLS, mkpool(limits=Resources.parse({"cpu": "4"})))
        for i, zone in enumerate(("zone-1a", "zone-1b", "zone-1c")):
            op.store.create(
                st.PODS, mkpod(f"p{i}", cpu="1", mem="1Gi", node_selector={wk.ZONE_LABEL: zone})
            )
        op.manager.settle()
        nodes = op.store.list(st.NODES)
        assert len(nodes) == 2  # third claim blocked by the limit
        pending = [p for p in op.store.list(st.PODS) if not p.bound]
        assert len(pending) == 1


class TestTerminationE2E:
    def test_delete_claim_drains_and_terminates(self, op):
        op.store.create(st.NODEPOOLS, mkpool(consolidation="WhenEmpty"))
        op.store.create(st.PODS, mkpod("p"))
        op.manager.settle()
        claim = op.store.list(st.NODECLAIMS)[0]
        node_name = claim.node_name
        old_instance = claim.provider_id.rsplit("/", 1)[-1]
        op.store.delete(st.NODECLAIMS, claim.name)
        op.manager.settle()
        assert op.store.try_get(st.NODES, node_name) is None
        assert not op.cloud.describe_instances([old_instance])  # terminated
        # the evicted pod went back to pending and got a NEW node
        pod = op.store.get(st.PODS, "p")
        assert pod.node_name is not None and pod.node_name != node_name

    def test_pdb_blocks_drain(self, op):
        op.store.create(st.NODEPOOLS, mkpool(consolidation="WhenEmpty"))
        op.store.create(
            st.PDBS,
            PodDisruptionBudget(
                meta=ObjectMeta(name="pdb"), selector={"app": "db"}, min_available=1
            ),
        )
        op.store.create(st.PODS, mkpod("db-0", labels={"app": "db"}))
        op.manager.settle()
        claim = op.store.list(st.NODECLAIMS)[0]
        node_name = claim.node_name
        op.store.delete(st.NODECLAIMS, claim.name)
        # settle: drain is blocked because evicting the only healthy db pod
        # would violate minAvailable=1 (there is nowhere else for it yet and
        # eviction counts it unavailable)
        op.manager.settle()
        assert op.store.try_get(st.NODES, node_name) is not None  # still alive


class TestDisruptionE2E:
    def test_empty_node_consolidated(self, op):
        op.store.create(st.NODEPOOLS, mkpool())
        op.store.create(st.PODS, mkpod("p"))
        op.manager.settle()
        assert len(op.store.list(st.NODES)) == 1
        # pod goes away; node is now empty -> emptiness deletes it
        pod = op.store.get(st.PODS, "p")
        pod.meta.finalizers = []
        op.store.delete(st.PODS, "p")
        op.clock.advance(30)
        op.manager.settle()
        assert not op.store.list(st.NODES)
        assert not op.store.list(st.NODECLAIMS)

    def test_do_not_disrupt_blocks(self, op):
        op.store.create(st.NODEPOOLS, mkpool())
        pod = mkpod("p")
        pod.meta.annotations[wk.DO_NOT_DISRUPT_ANNOTATION] = "true"
        op.store.create(st.PODS, pod)
        op.manager.settle()
        node = op.store.list(st.NODES)[0]
        # empty the node but mark node do-not-disrupt via the pod annotation:
        # pod still there -> not empty; instead annotate node and empty it
        p = op.store.get(st.PODS, "p")
        p.meta.finalizers = []
        op.store.delete(st.PODS, "p")
        node.meta.annotations[wk.DO_NOT_DISRUPT_ANNOTATION] = "true"
        op.store.update(st.NODES, node)
        op.clock.advance(30)
        op.manager.settle()
        assert len(op.store.list(st.NODES)) == 1  # survived

    def test_single_node_consolidation_replaces_with_cheaper(self, op):
        op.store.create(st.NODEPOOLS, mkpool())
        # force an oversized node by scheduling a big pod + a small one,
        # then delete the big pod: the small pod fits a much cheaper node
        op.store.create(st.PODS, mkpod("big", cpu="14", mem="24Gi"))
        op.store.create(st.PODS, mkpod("small", cpu="100m", mem="128Mi"))
        op.manager.settle()
        assert len(op.store.list(st.NODES)) == 1
        old_node = op.store.list(st.NODES)[0]
        old_price = op.store.list(st.NODECLAIMS)[0].price
        big = op.store.get(st.PODS, "big")
        big.meta.finalizers = []
        op.store.delete(st.PODS, "big")
        op.clock.advance(30)
        op.manager.settle()
        nodes = op.store.list(st.NODES)
        assert len(nodes) == 1
        assert nodes[0].meta.name != old_node.meta.name  # replaced
        new_claim = op.store.list(st.NODECLAIMS)[0]
        assert new_claim.price < old_price
        assert op.store.get(st.PODS, "small").node_name == nodes[0].meta.name

    def test_multi_node_consolidation(self, op):
        op.store.create(st.NODEPOOLS, mkpool())
        # create 3 nodes each holding one small pod by spreading via hostname
        from karpenter_tpu.api.objects import TopologySpreadConstraint

        tsc = TopologySpreadConstraint(
            max_skew=1, topology_key=wk.HOSTNAME_LABEL, label_selector={"app": "x"}
        )
        for i in range(3):
            op.store.create(
                st.PODS,
                mkpod(f"p{i}", cpu="200m", mem="256Mi", labels={"app": "x"},
                      topology_spread=[tsc]),
            )
        op.manager.settle()
        assert len(op.store.list(st.NODES)) == 3
        # drop the spread constraint: delete pods, recreate without TSC so
        # consolidation can pack them onto one node
        for i in range(3):
            p = op.store.get(st.PODS, f"p{i}")
            p.topology_spread = []
            op.store.update(st.PODS, p)
        op.clock.advance(30)
        op.manager.settle()
        nodes = op.store.list(st.NODES)
        assert len(nodes) < 3  # consolidated (>=2 deleted, <=1 replacement)
        pods = op.store.list(st.PODS)
        assert all(p.node_name for p in pods)


def test_round4_features_through_the_control_loop():
    """Integration: ct-spread, positive hostname affinity, and zone spread
    pods all converge through the FULL control loop (provisioner → launch →
    registration → binding) in one cluster — the features are end-to-end
    capabilities, not solver-only paths."""
    from karpenter_tpu.api.objects import PodAffinityTerm, TopologySpreadConstraint

    clock = FakeClock()
    op = new_kwok_operator(clock=clock)
    op.store.create(st.NODEPOOLS, mkpool())
    for i in range(6):
        p = mkpod(f"ct{i}", cpu="500m")
        p.meta.labels["tier"] = "ct"
        p.topology_spread = [TopologySpreadConstraint(
            max_skew=1, topology_key=wk.CAPACITY_TYPE_LABEL,
            label_selector={"tier": "ct"})]
        op.store.create(st.PODS, p)
    for i in range(4):
        p = mkpod(f"db{i}", cpu="250m")
        p.meta.labels["svc"] = "db"
        p.affinity_terms = [PodAffinityTerm(
            label_selector={"svc": "db"}, topology_key=wk.HOSTNAME_LABEL,
            anti=False)]
        op.store.create(st.PODS, p)
    for i in range(6):
        p = mkpod(f"zs{i}", cpu="500m")
        p.meta.labels["app"] = "zs"
        p.topology_spread = [TopologySpreadConstraint(
            max_skew=1, topology_key=wk.ZONE_LABEL,
            label_selector={"app": "zs"})]
        op.store.create(st.PODS, p)
    op.manager.settle()
    pods = op.store.list(st.PODS)
    bound = [p for p in pods if p.node_name]
    assert len(bound) == 16, [
        (p.meta.name, p.node_name) for p in pods if not p.node_name
    ]
    nodes = {n.meta.name: n for n in op.store.list(st.NODES)}
    # ct spread: both capacity types present among the ct pods' nodes
    cts = {
        nodes[p.node_name].meta.labels[wk.CAPACITY_TYPE_LABEL]
        for p in pods if p.meta.labels.get("tier") == "ct"
    }
    assert cts == {"on-demand", "spot"}, cts
    # hostname affinity: every db pod co-located on ONE node
    db_nodes = {p.node_name for p in pods if p.meta.labels.get("svc") == "db"}
    assert len(db_nodes) == 1, db_nodes
    # zone spread: the zs pods cover all three AZs (6 pods, maxSkew 1)
    zs_zones = {
        nodes[p.node_name].meta.labels[wk.ZONE_LABEL]
        for p in pods if p.meta.labels.get("app") == "zs"
    }
    assert len(zs_zones) == 3, zs_zones
