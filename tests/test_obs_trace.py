"""End-to-end solve tracing (obs/ — ISSUE 10): span-tree shape, trace
completeness across the pipeline and fleet layers, flight-recorder dumps
on fence, and the solve_id-keyed JSON log formatter.

The load-bearing contract: ONE ticket = ONE rooted span tree, no matter
how many threads (submitter, dispatcher, decoder, fleet watchdog) touched
the solve, and no orphan spans — every span's parent_id resolves inside
its own trace. A wedged solve must survive as a PARTIAL tree (open spans,
fault_site tagged) inside the fence's flight-recorder dump, then finish
"ok" after the requeue with a requeued_from link naming the fenced owner.
"""

import glob
import json
import logging
import os
import random
import threading

import pytest

from karpenter_tpu import faults
from karpenter_tpu.obs import trace as obstrace
from karpenter_tpu.obs.export import chrome_trace
from karpenter_tpu.obs.logjson import JsonLogFormatter
from karpenter_tpu.obs.recorder import FlightRecorder
from karpenter_tpu.solver.backend import ReferenceSolver
from karpenter_tpu.solver.pipeline import (
    DISRUPTION,
    PROVISIONING,
    SolveService,
    Superseded,
)

from tests.test_solver_fleet import TaggedOracle, mkfleet, mkinput


@pytest.fixture
def tracing(tmp_path):
    """Enabled tracing with a per-test flight recorder; always restores
    the import-time default (disabled, no recorder) afterwards."""
    rec = FlightRecorder(dir=str(tmp_path), min_interval_s=0.0)
    obstrace.configure(enabled=True, ring=128, recorder=rec)
    try:
        yield rec
    finally:
        obstrace.configure(enabled=False, recorder=None)


def _assert_rooted(snap):
    """One root, every other span's parent_id resolves in-trace."""
    ids = {sp["span_id"] for sp in snap["spans"]}
    roots = [sp for sp in snap["spans"] if sp["parent_id"] is None]
    assert len(roots) == 1, snap
    assert roots[0]["name"] == "solve"
    for sp in snap["spans"]:
        if sp["parent_id"] is not None:
            assert sp["parent_id"] in ids, f"orphan span {sp}"


# ------------------------------------------------------------------ primitives


def test_span_tree_basics(tracing):
    tr = obstrace.begin("provisioning")
    assert tr.solve_id.startswith("s")
    with obstrace.attached(tr):
        assert obstrace.current_solve_id() == tr.solve_id
        with obstrace.span("outer") as outer:
            obstrace.annotate(k=1)
            with obstrace.span("inner"):
                pass
            obstrace.event("marker", why="test")
    tr.add_link("requeued_from", "owner-9")
    obstrace.finish(tr, "ok")
    obstrace.finish(tr, "error")  # idempotent: first status wins

    snap = tr.snapshot()
    _assert_rooted(snap)
    by_name = {sp["name"]: sp for sp in snap["spans"]}
    assert by_name["outer"]["parent_id"] == by_name["solve"]["span_id"]
    assert by_name["inner"]["parent_id"] == by_name["outer"]["span_id"]
    assert by_name["marker"]["parent_id"] == by_name["outer"]["span_id"]
    assert by_name["outer"]["attrs"] == {"k": 1}
    assert snap["links"] == {"requeued_from": ["owner-9"]}
    assert snap["status"] == "ok" and snap["done"]
    assert outer.duration_s >= 0
    assert tr in obstrace.recent()
    assert tr not in obstrace.active_traces()


def test_disabled_and_unattached_paths_are_null():
    obstrace.configure(enabled=False)
    assert obstrace.begin("solve") is None
    with obstrace.span("x") as sp:
        assert sp is None
    obstrace.annotate(k=1)  # no-op, no crash
    obstrace.event("e")
    obstrace.finish(None)
    assert obstrace.dump("nothing") is None
    obstrace.configure(enabled=True)
    try:
        # enabled but thread unattached: still the shared null context —
        # direct solver.solve() calls outside a ticket produce no orphans
        with obstrace.span("x") as sp:
            assert sp is None
        assert obstrace.current_trace() is None
    finally:
        obstrace.configure(enabled=False)


def test_status_of_maps_ticket_errors():
    class Superseded(Exception):
        pass

    class ServiceStopped(Exception):
        pass

    assert obstrace.status_of(None) == "ok"
    assert obstrace.status_of(Superseded()) == "superseded"
    assert obstrace.status_of(ServiceStopped()) == "stopped"
    assert obstrace.status_of(ValueError("x")) == "error"


def test_active_set_bounded_by_eviction(tracing):
    for _ in range(obstrace._ACTIVE_MAX + 10):
        obstrace.begin("solve")
    assert len(obstrace.active_traces()) <= obstrace._ACTIVE_MAX
    assert any(t.status == "abandoned" for t in obstrace.recent())


def test_concurrent_annotate_never_breaks_snapshot(tracing):
    """annotate() inserts span attrs while another thread snapshots the
    trace (flight-recorder dump of active_traces, GET /debug/trace) —
    snapshot must never iterate a dict mid-mutation. The writer is
    BOUNDED (fixed insert count) and the reader loops until it finishes:
    snapshot cost grows with the dict, so an unbounded writer livelocks
    a single-core box."""
    tr = obstrace.begin("solve")
    done = threading.Event()

    def writer():
        with obstrace.attached(tr):
            for i in range(50_000):
                obstrace.annotate(**{f"k{i}": i})  # fresh key = dict resize
        done.set()

    t = threading.Thread(target=writer, daemon=True)
    t.start()
    try:
        while not done.is_set():
            tr.snapshot()  # pre-fix: RuntimeError (dict changed size)
        tr.snapshot()
    finally:
        done.set()
        t.join(10)
        obstrace.finish(tr, "ok")


def test_dump_failure_never_escapes(tracing):
    """A trace whose snapshot blows up mid-dump must not propagate out of
    obstrace.dump(): its callers are recovery paths (fence, breaker open,
    gate reject) whose forward progress can't depend on diagnostics."""

    class _Evil:
        solve_id = "evil"

        def snapshot(self):
            raise RuntimeError("dictionary changed size during iteration")

    obstrace._ACTIVE["evil"] = _Evil()
    try:
        assert obstrace.dump("fleet_fence", owner="owner-0") is None
    finally:
        obstrace._ACTIVE.pop("evil", None)
    assert tracing.health()["dumps"] == 0


# ------------------------------------------------------- pipeline completeness


def test_single_pipeline_solve_one_rooted_tree(tracing):
    svc = SolveService(ReferenceSolver(), depth=2)
    try:
        tk = svc.submit(mkinput("one"), kind=DISRUPTION)
        tk.result(timeout=10)
    finally:
        svc.close()
    traces = [t for t in obstrace.recent() if t.solve_id == tk.solve_id]
    assert len(traces) == 1
    snap = traces[0].snapshot()
    _assert_rooted(snap)
    names = {sp["name"] for sp in snap["spans"]}
    assert {"pipeline.queue", "pipeline.dispatch", "pipeline.decode"} <= names
    assert snap["status"] == "ok"
    # the tree genuinely crossed threads (submit vs dispatcher/decoder)
    assert len({sp["thread"] for sp in snap["spans"]}) >= 2
    assert not obstrace.active_traces()


def test_randomized_pipeline_fleet_trace_completeness(tracing):
    """Randomized solves through BOTH layers: every ticket yields exactly
    one rooted tree, superseded/stopped included, and no trace leaks in
    the active set once everything resolved."""
    rng = random.Random(7)
    svc = SolveService(ReferenceSolver(), depth=2)
    fleet, _solvers, _clock = mkfleet(size=2)
    tickets = []
    try:
        for i in range(24):
            inp = mkinput(f"p{i}", cpu=rng.choice(["100m", "250m", "500m"]))
            if rng.random() < 0.5:
                if rng.random() < 0.4:
                    tickets.append(svc.submit(inp, kind=PROVISIONING, rev=i))
                else:
                    tickets.append(svc.submit(inp, kind=DISRUPTION))
            else:
                tickets.append(fleet.submit(inp, kind=DISRUPTION))
        for tk in tickets:
            try:
                tk.result(timeout=20)
            except Superseded:
                pass
    finally:
        svc.close()
        fleet.close()

    finished = {t.solve_id: t for t in obstrace.recent()}
    assert not obstrace.active_traces(), "traces leaked in the active set"
    seen_statuses = set()
    for tk in tickets:
        assert tk.solve_id in finished, f"ticket {tk.solve_id} has no trace"
        snap = finished[tk.solve_id].snapshot()
        _assert_rooted(snap)
        assert snap["done"]
        seen_statuses.add(snap["status"])
    assert len({tk.solve_id for tk in tickets}) == len(tickets)
    assert "ok" in seen_statuses
    # the Chrome export of the whole run is loadable and keeps every
    # event correlated to its solve
    doc = chrome_trace(list(finished.values()))
    doc = json.loads(json.dumps(doc))  # round-trips as pure JSON
    assert all(e["args"]["solve_id"] in finished
               for e in doc["traceEvents"] if e["ph"] != "M")


# ------------------------------------------- wedge -> fence -> dump -> requeue


def test_fence_dumps_wedged_solve_then_requeue_finishes_tree(tracing, tmp_path):
    plan = faults.FaultPlan()
    wedge = plan.wedge("solver.device_hang", tag="owner-0")
    with faults.active(plan):
        fleet, _solvers, _clock = mkfleet(size=2)
        try:
            tk = fleet.submit(mkinput("wedged"))
            v1 = fleet.probe_once()
            v2 = fleet.probe_once()
            assert v1["owner-0"] == "miss" and v2["owner-0"] == "fenced", (v1, v2)
            tk.result(timeout=20)  # requeued onto owner-1 and delivered
        finally:
            wedge.release()
            fleet.close()

    dumps = glob.glob(os.path.join(str(tmp_path), "*fleet_fence*"))
    assert len(dumps) >= 1
    d = json.load(open(dumps[0]))
    assert d["reason"] == "fleet_fence"
    assert d["tags"]["owner"] == "owner-0"
    assert d["tags"]["requeued"] >= 1
    assert len(d["canary_history"]) >= 2
    # the wedged solve is in the dump as a PARTIAL tree: root still open,
    # the parked stage tagged with the fault site
    partial = [t for t in d["partial_traces"] if t["solve_id"] == tk.solve_id]
    assert partial, d["partial_traces"]
    snap = partial[0]
    _assert_rooted(snap)
    assert any(sp["t1"] is None for sp in snap["spans"]), "nothing open"
    assert any(sp["attrs"].get("fault_site") == "solver.device_hang"
               for sp in snap["spans"])
    # after the requeue the SAME trace finished ok, carrying the history
    done = [t for t in obstrace.recent() if t.solve_id == tk.solve_id]
    assert len(done) == 1
    assert done[0].status == "ok"
    assert done[0].links.get("requeued_from") == ["owner-0"]
    # flight-recorder health surfaced the dump
    health = tracing.health()
    assert health["dumps"] >= 1
    assert health["last_dump"]["reason"] == "fleet_fence"


def test_fence_survives_recorder_failure(tmp_path):
    """A diagnostics failure mid-fence must not strand survivors: the
    wedged owner's service is still stopped and its outstanding requests
    still re-routed even when the flight-recorder dump itself raises
    (pre-fix: the exception escaped after fenced=True, so the ticket
    blocked forever and re-entering _fence early-returned)."""

    class ExplodingRecorder(FlightRecorder):
        def dump(self, reason, tags=None):
            raise RuntimeError("boom while building the dump payload")

    obstrace.configure(enabled=True, ring=128,
                       recorder=ExplodingRecorder(dir=str(tmp_path)))
    plan = faults.FaultPlan()
    wedge = plan.wedge("solver.device_hang", tag="owner-0")
    try:
        with faults.active(plan):
            fleet, _solvers, _clock = mkfleet(size=2)
            try:
                tk = fleet.submit(mkinput("wedged"))
                v1 = fleet.probe_once()
                v2 = fleet.probe_once()
                assert v1["owner-0"] == "miss" and v2["owner-0"] == "fenced"
                assert tk.result(timeout=20) is not None  # requeued, not stranded
            finally:
                wedge.release()
                fleet.close()
    finally:
        obstrace.configure(enabled=False, recorder=None)


def test_superseded_request_closes_queue_span(tracing):
    """The coalesce path ends the stale request's pipeline.queue span
    ('superseded'), so its trace never exports an unterminated event."""
    from tests.test_solve_pipeline import GatedAsyncSolver

    solver = GatedAsyncSolver()
    svc = SolveService(solver, depth=2)
    try:
        t1 = svc.submit(mkinput("p1"), kind=PROVISIONING)
        assert solver.dispatching.wait(10)  # p1 popped: no longer coalescible
        t2 = svc.submit(mkinput("p2"), kind=PROVISIONING)
        t3 = svc.submit(mkinput("p3"), kind=PROVISIONING)  # supersedes t2
        assert t2.done() and t2.superseded()
        solver.gate.set()
        t1.result(timeout=10)
        t3.result(timeout=10)
    finally:
        solver.gate.set()
        svc.close()

    done = {t.solve_id: t for t in obstrace.recent()}
    snap = done[t2.solve_id].snapshot()
    assert snap["status"] == "superseded"
    qspans = [sp for sp in snap["spans"] if sp["name"] == "pipeline.queue"]
    assert len(qspans) == 1
    assert qspans[0]["t1"] is not None, "queue span left open"
    assert qspans[0]["status"] == "superseded"


def test_wedged_fleet_trace_annotates_fault_before_parking(tracing):
    """The fault site lands on the span tree BEFORE the thread parks, so
    active_traces() shows where a still-wedged solve is stuck (what the
    dump captures mid-fence)."""
    plan = faults.FaultPlan()
    wedge = plan.wedge("solver.device_hang")
    oracle = TaggedOracle()
    done = threading.Event()
    tr = obstrace.begin("disruption")

    def run():
        with faults.active(plan):
            with obstrace.attached(tr), obstrace.span("pipeline.decode"):
                oracle.solve(mkinput("stuck"))
        done.set()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    try:
        for _ in range(200):
            snap = tr.snapshot()
            hit = [sp for sp in snap["spans"]
                   if sp["attrs"].get("fault_site") == "solver.device_hang"]
            if hit:
                break
            import time
            time.sleep(0.01)
        assert hit and hit[0]["t1"] is None
        assert tr in obstrace.active_traces()
    finally:
        wedge.release()
        done.wait(10)
        obstrace.finish(tr, "ok")


# ---------------------------------------------------------- JSON log formatter


def _format(record_args, extra=None):
    rec = logging.LogRecord("karpenter_tpu", logging.INFO, __file__, 1,
                            record_args, (), None)
    for k, v in (extra or {}).items():
        setattr(rec, k, v)
    return json.loads(JsonLogFormatter().format(rec))


def test_json_formatter_explicit_solve_id_wins(tracing):
    out = _format("fenced owner", extra={"solve_id": "s000042"})
    assert out["solve_id"] == "s000042"
    assert out["msg"] == "fenced owner"
    assert out["level"] == "info" and out["logger"] == "karpenter_tpu"


def test_json_formatter_picks_up_ambient_trace(tracing):
    tr = obstrace.begin("provisioning")
    with obstrace.attached(tr):
        out = _format("inside the solve")
    obstrace.finish(tr, "ok")
    assert out["solve_id"] == tr.solve_id
    # outside any trace the key is simply absent, not null
    out = _format("background housekeeping")
    assert "solve_id" not in out


def test_json_formatter_exception_lines():
    try:
        raise RuntimeError("boom")
    except RuntimeError:
        import sys
        rec = logging.LogRecord("karpenter_tpu", logging.ERROR, __file__, 1,
                                "solve failed", (), sys.exc_info())
    out = json.loads(JsonLogFormatter().format(rec))
    assert "RuntimeError: boom" in out["exc"]
    assert "\n" not in json.dumps(out["msg"])  # one record = one line


# ------------------------------------------------------- fused cohort dispatch


def test_fused_cohort_dispatch_trace_completeness(tracing):
    """A fused cohort dispatch emits exactly ONE cohort.dispatch span — on
    the lead member's trace, carrying every member solve_id — while EACH
    member keeps its own independently-rooted, independently-closing tree
    with its own fetch/decode spans (SPEC.md "Cohort semantics")."""
    from karpenter_tpu.provisioning.scheduler import SolverInput
    from karpenter_tpu.solver.backend import TPUSolver
    from tests.test_batched_consolidation import ZONES, mkpod, pool

    inps = [
        SolverInput(pods=[mkpod(f"co-{i}-a"), mkpod(f"co-{i}-b")],
                    nodes=[], nodepools=[pool()], zones=ZONES)
        for i in range(3)
    ]
    traces = [obstrace.begin(DISRUPTION) for _ in inps]
    s = TPUSolver()
    outs = s.solve_cohort_async(inps, traces=traces)()
    assert s.stats["fused_dispatches"] == 1
    assert s.stats["fused_members"] == 3
    for tr, out in zip(traces, outs):
        assert not isinstance(out, Exception), out
        obstrace.finish(tr, "ok")
    snaps = [tr.snapshot() for tr in traces]
    for snap in snaps:
        _assert_rooted(snap)
        assert snap["done"] and snap["status"] == "ok"
        names = {sp["name"] for sp in snap["spans"]}
        # every member decodes on its OWN trace
        assert {"backend.fetch", "backend.decode"} <= names, names
    cds = [sp for snap in snaps for sp in snap["spans"]
           if sp["name"] == "cohort.dispatch"]
    assert len(cds) == 1, "exactly one fused-dispatch span across members"
    assert cds[0]["attrs"]["cohort_size"] == 3
    members = cds[0]["attrs"]["member_solve_ids"].split(",")
    assert members == [tr.solve_id for tr in traces]
    assert not obstrace.active_traces()
