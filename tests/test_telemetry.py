"""Runtime health plane: recompile detector, arena byte budget, anomaly
engine, and the /debug/vars telemetry ring (ISSUE 14).

ISSUE 14 acceptance:
- the hot-path recompile detector stays SILENT across the whole existing
  kernel matrix (solve / ckpt+resume / ladder / shard / gang / preemption /
  explain / apply_events) re-dispatched at identical shapes, and catches an
  injected signature-perturbing dispatch with exactly one hot_path event,
  a /healthz WARN, and one (per-reason throttled) flight-recorder dump
  carrying the arg-signature diff;
- a byte-budgeted arena evicts cold buckets and STILL decides bit-identically
  to an unbudgeted solver — eviction means a cold re-upload, never a wrong
  answer — while total accounted bytes stay under the budget;
- the rolling-baseline anomaly engine trips after `sustain` breaches,
  recovers after `recover` clean observations, and throttles its flight
  dumps per stage — all driven by an injected fake clock;
- /debug/vars serves the ring as JSON (window param clamped, 400 on junk).
"""

import glob
import json
import os

import jax.numpy as jnp
import numpy as np
import pytest

from karpenter_tpu.metrics.registry import (
    SOLVER_ARENA_EVICTIONS,
    SOLVER_PERF_ANOMALIES,
)
from karpenter_tpu.obs import anomaly as obsanomaly
from karpenter_tpu.obs import explain as obsexplain
from karpenter_tpu.obs import telemetry as obstelemetry
from karpenter_tpu.obs import trace as obstrace
from karpenter_tpu.obs.recorder import FlightRecorder
from karpenter_tpu.operator.__main__ import serve_endpoints
from karpenter_tpu.provisioning.scheduler import SolverInput
from karpenter_tpu.solver import scheduling_class as sc
from karpenter_tpu.solver.backend import TPUSolver
from karpenter_tpu.solver.tpu import ffd

from tests.test_e2e_kwok import FakeClock
from tests.test_metrics_endpoint import _get
from tests.test_scan_resume import _add_replica, _fleet, _warm_solver
from tests.test_scheduling_class import gang_labels, mknode, victim
from tests.test_solver_parity import ZONES, mkpod, pool
from tests.test_transfer_arena import _assert_same, _inp


@pytest.fixture(autouse=True)
def _fresh_health_plane():
    """Boot-state health plane per test; restore module-import defaults
    after (prewarm not done, detector empty, no recorder, explain off)."""
    obstelemetry.configure()
    obsanomaly.configure()
    obstrace.configure()
    yield
    obstelemetry.configure()
    obsanomaly.configure()
    obstrace.configure()
    obsexplain.configure(enabled=False)


def _stub_kernel():
    """A plain callable shaped like a jitted entry (has __wrapped__) so
    detector semantics can be driven without paying an XLA compile."""

    def fn(*args, **kwargs):
        return 0

    fn.__wrapped__ = fn
    return fn


# -- recompile detector ------------------------------------------------------


def test_kernel_matrix_stays_silent_at_fixed_buckets():
    """Round 1 dispatches every jitted entry point in the matrix before the
    prewarm boundary (compiles are expected, kind=prewarm); round 2 repeats
    the IDENTICAL inputs on fresh solver instances after mark_prewarm_done()
    — every signature is on record, so the hot-path detector must not fire
    once across the whole matrix."""
    sc.configure(preemption=True, gang=True)
    obsexplain.configure(enabled=True, top_k=8)
    try:

        def drive():
            # ffd_solve (+ explain_pack: capture is enabled)
            TPUSolver(resume=False).solve(_inp(12))
            # ffd_solve_ckpt then ffd_resume via an append-tail warm solve
            warm = _warm_solver()
            base = _fleet(n_specs=8, prefix="t")
            warm.solve(base)
            warm.solve(_add_replica(base, 2, "t-extra"))
            assert warm.stats["resume_solves"] == 1, warm.stats
            # ffd_solve_ladder: soft topology spread engages the relax ladder
            from karpenter_tpu.api.objects import TopologySpreadConstraint

            sel = {"app": "soft"}
            soft = [
                mkpod(f"s{i}", labels=dict(sel), topology_spread=[
                    TopologySpreadConstraint(
                        max_skew=1,
                        topology_key="topology.kubernetes.io/zone",
                        label_selector=sel,
                        when_unsatisfiable="ScheduleAnyway")])
                for i in range(3)
            ]
            TPUSolver(relax_ladder=True).solve(SolverInput(
                pods=soft, nodes=[], nodepools=[pool()], zones=ZONES))
            # ffd_solve_sharded: enough distinct runs to split across shards
            mixed = [
                mkpod(f"m{i:03d}", cpu=["250m", "500m", "1", "2"][i % 4],
                      mem=["512Mi", "1Gi", "2Gi"][i % 3])
                for i in range(60)
            ]
            TPUSolver(shards=2).solve(SolverInput(
                pods=mixed, nodes=[], nodepools=[pool()], zones=ZONES))
            # gang_commit (device planner) over an all-placed gang
            gang = [mkpod(f"g{i}", cpu="500m", labels=gang_labels("job", 4))
                    for i in range(4)]
            sc.ClassAwareSolver(TPUSolver()).solve(SolverInput(
                pods=gang, nodes=[], nodepools=[pool()], zones=ZONES))
            # preemption_plan (device planner): full node + eligible victims
            node = mknode("n0", cpu="0", mem="0Mi", victims=[
                victim("v-a", priority=1), victim("v-b", priority=2)])
            hi = mkpod("hi", cpu="2", mem="2Gi", priority=100)
            sc.ClassAwareSolver(TPUSolver()).solve(SolverInput(
                pods=[hi], nodes=[node], nodepools=[], zones=ZONES))
            # ffd_apply_events (streaming run-table scatter)
            ev = jnp.array([[0, 1, 2], [3, 2, 1]], jnp.int32)
            assert ev.shape[1] == ffd.EVENT_ENTRY_WORDS
            ffd.ffd_apply_events(
                jnp.zeros(16, jnp.int32), jnp.zeros(16, jnp.int32), ev)

        drive()
        seen = set(obstelemetry.snapshot()["compiles"])
        want = {"ffd_solve", "ffd_solve_ckpt", "ffd_resume",
                "ffd_solve_ladder", "ffd_solve_sharded", "gang_commit",
                "preemption_plan", "explain_pack", "ffd_apply_events"}
        assert want <= seen, f"matrix missed kernels: {want - seen}"
        assert obstelemetry.stats["hot_path_compiles"] == 0

        obstelemetry.mark_prewarm_done()
        drive()
        assert obstelemetry.stats["hot_path_compiles"] == 0, (
            obstelemetry.hot_path_records())
        assert obstelemetry.health()["state"] == "ok"
    finally:
        sc.configure(preemption=True, gang=True)
        obsexplain.configure(enabled=False)


def test_hot_path_recompile_detected_warned_and_dump_throttled(tmp_path):
    """A post-prewarm dispatch at an unseen signature is a defect: exactly
    one hot_path event with the arg diff, /healthz WARNs, and ONE flight
    dump (reason `recompile`) — further offenders inside the per-reason
    throttle window are counted but not dumped, until the window reopens."""
    clock = FakeClock()
    obstrace.configure(enabled=True, recorder=FlightRecorder(
        dir=str(tmp_path), clock=clock))

    s = TPUSolver()
    s.solve(_inp(40))
    obstelemetry.mark_prewarm_done()
    s.solve(_inp(40))  # identical bucket: silent
    assert obstelemetry.stats["hot_path_compiles"] == 0
    assert obstelemetry.health()["state"] == "ok"

    s.solve(_inp(40, specs=20))  # bucket change post-prewarm: the defect
    assert obstelemetry.stats["hot_path_compiles"] == 1
    rec = obstelemetry.hot_path_records()[-1]
    assert rec["kernel"] == "ffd_solve_ckpt" and rec["diff"], rec
    health = obstelemetry.health()
    assert health["state"] == "warn"
    assert "hot_path_recompiles" in health["warnings"]
    dumps = glob.glob(os.path.join(str(tmp_path), "*-recompile.json"))
    assert len(dumps) == 1, dumps
    with open(dumps[0]) as f:
        payload = json.load(f)
    # the dump carries the telemetry snapshot (ISSUE 14 satellite)
    assert payload.get("telemetry"), list(payload)

    # second offender while the recompile throttle window is closed: the
    # event is recorded, the dump is suppressed
    probe = obstelemetry.instrument("probe_throttle", _stub_kernel())
    probe(np.zeros((3, 3), np.int32))
    assert obstelemetry.stats["hot_path_compiles"] == 2
    assert len(glob.glob(os.path.join(str(tmp_path), "*-recompile.json"))) == 1

    clock.advance(61.0)  # reopen the 60s per-reason window
    probe(np.zeros((4, 4), np.int32))
    assert obstelemetry.stats["hot_path_compiles"] == 3
    assert len(glob.glob(os.path.join(str(tmp_path), "*-recompile.json"))) == 2


def test_instrument_is_idempotent_and_off_path_is_inert():
    fn = _stub_kernel()
    hook = obstelemetry.instrument("probe_inert", fn)
    assert obstelemetry.instrument("probe_inert", hook) is hook
    assert hook.__wrapped__ is fn  # vmap/introspection contract

    obstelemetry.configure(enabled=False)
    before = dict(obstelemetry.stats)
    hook(np.zeros((2, 2), np.float32))
    assert obstelemetry.stats == before  # no check, no compile recorded


def test_prewarm_coverage_and_failures_warn():
    obstelemetry.note_prewarm(4, 3)
    obstelemetry.note_prewarm_failure("M=64,zone_engine=False",
                                      RuntimeError("boom"))
    health = obstelemetry.health()
    assert health["state"] == "warn"
    assert {"prewarm_coverage", "prewarm_failures"} <= set(health["warnings"])
    assert health["prewarm"]["coverage"] == 0.75
    assert health["prewarm"]["failures"] == 1


# -- arena byte budget -------------------------------------------------------


def test_arena_budget_evicts_cold_and_preserves_decisions():
    """With the budget pinned to exactly one resident bucket, alternating
    buckets forces evict + cold re-upload on every swap — decisions must
    stay bit-identical to an unbudgeted control solver, accounted bytes
    must never exceed the budget, and every eviction is counted."""
    budgeted, control = TPUSolver(), TPUSolver()
    a, b = _inp(40), _inp(40, specs=20)  # two distinct shape buckets

    _assert_same(budgeted.solve(a), control.solve(a), "cold")
    budget = budgeted.arena.total_bytes()
    assert budget > 0
    budgeted.arena.budget_bytes = budget

    ev0 = budgeted.arena.stats["evictions"]
    ctr0 = SOLVER_ARENA_EVICTIONS.value()
    for tag, inp in (("bucket-b", b), ("back-to-a", a), ("b-again", b)):
        _assert_same(budgeted.solve(inp), control.solve(inp), tag)
        assert budgeted.arena.total_bytes() <= budget, tag
    assert budgeted.arena.stats["evictions"] - ev0 >= 2
    assert SOLVER_ARENA_EVICTIONS.value() - ctr0 >= 2
    # the class breakdown is the accounting of record: it sums to the total
    assert budgeted.arena.total_bytes() == sum(
        budgeted.arena.bytes_by_class().values())
    # the control solver was never evicted
    assert control.arena.stats["evictions"] == 0


# -- rolling-baseline anomaly engine -----------------------------------------


def test_anomaly_trip_recover_and_dump_throttle(tmp_path):
    """Fake-clock driven: `sustain` breaches trip the stage (counter + warn
    + one perf_anomaly flight dump), `recover` clean observations clear it,
    and a re-trip inside the per-stage dump interval is counted but not
    dumped until the clock advances past it. Breach magnitudes escalate per
    trip so the slow-adapting (alpha/8) baseline can never catch up."""
    clock = FakeClock()
    obstrace.configure(enabled=True, recorder=FlightRecorder(
        dir=str(tmp_path), clock=clock))
    obsanomaly.configure(multiplier=3.0, sustain=3, recover=4, min_samples=5,
                         dump_interval_s=60.0, clock=clock)

    def dumps():
        return glob.glob(os.path.join(str(tmp_path), "*-perf_anomaly.json"))

    for _ in range(10):  # warm-up: flat 10ms baseline
        obsanomaly.observe("sched.solve", 0.010)
    warm = obsanomaly.health()
    assert warm["state"] == "ok"
    assert not warm["stages"]["sched.solve"]["anomalous"]
    assert warm["stages"]["sched.solve"]["samples"] == 10

    trips0 = SOLVER_PERF_ANOMALIES.value(stage="sched.solve")
    for _ in range(3):  # sustain=3 breaches -> trip
        obsanomaly.observe("sched.solve", 1e3)
    health = obsanomaly.health()
    assert health["state"] == "warn"
    assert health["stages"]["sched.solve"]["anomalous"]
    assert health["stages"]["sched.solve"]["trips"] == 1
    assert SOLVER_PERF_ANOMALIES.value(stage="sched.solve") - trips0 == 1
    assert len(dumps()) == 1
    with open(dumps()[0]) as f:
        payload = json.load(f)
    assert payload["tags"]["stage"] == "sched.solve"
    assert payload["tags"]["observed_ms"] > payload["tags"]["baseline_ms"]

    for _ in range(4):  # recover=4 clean observations
        obsanomaly.observe("sched.solve", 0.010)
    assert obsanomaly.health()["state"] == "ok"
    assert not obsanomaly.health()["stages"]["sched.solve"]["anomalous"]

    for _ in range(3):  # re-trip inside the 60s dump window: throttled
        obsanomaly.observe("sched.solve", 1e6)
    assert obsanomaly.health()["stages"]["sched.solve"]["trips"] == 2
    assert len(dumps()) == 1

    clock.advance(61.0)
    for _ in range(4):
        obsanomaly.observe("sched.solve", 0.010)
    for _ in range(3):  # third trip, window reopened: second dump
        obsanomaly.observe("sched.solve", 1e9)
    assert obsanomaly.health()["stages"]["sched.solve"]["trips"] == 3
    assert len(dumps()) == 2


def test_anomaly_disabled_is_inert():
    obsanomaly.configure(enabled=False)
    for _ in range(50):
        obsanomaly.observe("stage.x", 1e9)
    assert obsanomaly.health() == {"state": "ok", "stages": {}}


# -- telemetry ring / gauges / providers -------------------------------------


def test_ring_sample_carries_gauges_events_and_providers():
    obstelemetry.set_gauge("arena_bytes_total", 123.0)
    obstelemetry.note_event("fleet_fence", owner="solver-1", reason="probe")
    obstelemetry.register_provider("p", lambda: {"ok": True})
    snap = obstelemetry.sample()
    assert snap["gauges"]["arena_bytes_total"] == 123.0
    assert snap["events"][-1]["event"] == "fleet_fence"
    assert snap["events"][-1]["owner"] == "solver-1"
    assert snap["providers"]["p"] == {"ok": True}
    assert obstelemetry.recent_samples(1) == [snap]

    # a broken provider is contained, never takes down the snapshot
    obstelemetry.register_provider("bad", lambda: 1 / 0)
    got = obstelemetry.provider_result("bad")
    assert "error" in got and "ZeroDivisionError" in got["error"]
    assert obstelemetry.provider_result("missing") is None


def test_maybe_sample_throttles_on_injected_clock():
    clock = FakeClock()
    obstelemetry.configure(sample_interval_s=10.0, clock=clock)
    obstelemetry.maybe_sample()
    obstelemetry.maybe_sample()  # inside the interval: skipped
    assert obstelemetry.stats["samples"] == 1
    clock.advance(10.0)
    obstelemetry.maybe_sample()
    assert obstelemetry.stats["samples"] == 2


# -- endpoints ---------------------------------------------------------------


@pytest.fixture()
def server():
    srv = serve_endpoints(0, 0, enable_profiling=False)
    yield srv.server_address[1]
    srv.shutdown()


def test_debug_vars_endpoint_matrix(server):
    for _ in range(3):
        obstelemetry.sample()
    status, ctype, body = _get(server, "/debug/vars")
    assert status == 200 and ctype == "application/json"
    payload = json.loads(body)
    assert "now" in payload and len(payload["samples"]) >= 3
    assert payload["now"]["enabled"] is True

    status, _, body = _get(server, "/debug/vars?window=2")
    assert status == 200 and len(json.loads(body)["samples"]) == 2

    status, _, body = _get(server, "/debug/vars?window=-3")
    assert status == 200  # clamped to 1
    assert len(json.loads(body)["samples"]) == 1

    status, _, _ = _get(server, "/debug/vars?window=nope")
    assert status == 400


def test_healthz_worst_of_health_planes(server):
    obstelemetry.register_provider("streaming", lambda: {"journal": {"lag": 0}})
    status, _, body = _get(server, "/healthz")
    payload = json.loads(body)
    assert status == 200
    assert payload["status"] == "ok"
    assert payload["telemetry"]["state"] == "ok"
    assert payload["anomaly"]["state"] == "ok"
    assert payload["streaming"] == {"journal": {"lag": 0}}

    # one hot-path recompile flips the worst-of status to warn
    obstelemetry.mark_prewarm_done()
    probe = obstelemetry.instrument("probe_hz", _stub_kernel())
    probe(np.zeros((2, 2), np.int32))
    payload = json.loads(_get(server, "/healthz")[2])
    assert payload["status"] == "warn"
    assert "hot_path_recompiles" in payload["telemetry"]["warnings"]
