"""Differential test: batched consolidation verdicts vs sequential simulate.

The batched evaluator (disruption/batched.py) must reach the same verdict the
sequential path reaches: re-solve with the candidates' pods pending and the
candidate nodes REMOVED. Zone-constrained workloads are the regression
surface — the batched path keeps candidate nodes in the tensors (compat-
masked) while their bound pods are re-posed as pending, so the initial zone
counts must subtract the candidates' contributions per subset or verdicts
double-count them (VERDICT r3 "what's weak" #1: silently missed
consolidations). Both accept AND reject outcomes are asserted.
"""

import dataclasses

import pytest

from karpenter_tpu.api import wellknown as wk
from karpenter_tpu.api.objects import (
    ObjectMeta,
    Pod,
    PodAffinityTerm,
    TopologySpreadConstraint,
)
from karpenter_tpu.catalog.catalog import CatalogSpec, generate
from karpenter_tpu.disruption.batched import BatchedConsolidationEvaluator
from karpenter_tpu.provisioning.scheduler import ExistingNode, NodePoolSpec, SolverInput
from karpenter_tpu.scheduling.requirements import IN, Requirement, Requirements
from karpenter_tpu.solver.backend import ReferenceSolver, TPUSolver, quantize_input
from karpenter_tpu.utils.resources import Resources

CATALOG = generate(CatalogSpec())
ZONES = ("zone-1a", "zone-1b", "zone-1c")


def pool(name="default", reqs=None):
    r = Requirements.of(Requirement.create(wk.NODEPOOL_LABEL, IN, [name]))
    if reqs:
        r = r.union(reqs)
    return NodePoolSpec(
        name=name, weight=0, requirements=r, taints=[], instance_types=CATALOG
    )


def mkpod(name, cpu="500m", mem="512Mi", labels=None, **kw):
    return Pod(
        meta=ObjectMeta(name=name, uid=name, labels=labels or {}),
        requests=Resources.parse({"cpu": cpu, "memory": mem}),
        **kw,
    )


def mknode(nid, zone, free_cpu="8", free_mem="32Gi", pod_labels=None):
    free = Resources.parse({"cpu": free_cpu, "memory": free_mem})
    free["pods"] = 110
    return ExistingNode(
        id=nid,
        labels={
            wk.ZONE_LABEL: zone,
            wk.CAPACITY_TYPE_LABEL: "on-demand",
            wk.HOSTNAME_LABEL: nid,
            wk.ARCH_LABEL: "amd64",
            wk.OS_LABEL: "linux",
        },
        taints=[],
        free=free,
        pod_labels=list(pod_labels or []),
    )


def sequential_verdict(base: SolverInput, candidate_pods, candidate_node, subset):
    """Mirror DisruptionController._simulate: candidates' pods pending,
    candidate nodes removed, solved by the reference oracle."""
    pods = [
        dataclasses.replace(p, node_name=None, phase="Pending")
        for cid in subset
        for p in candidate_pods[cid]
    ]
    removed = {candidate_node[cid] for cid in subset}
    inp = dataclasses.replace(
        base,
        pods=pods,
        nodes=[n for n in base.nodes if n.id not in removed],
    )
    res = ReferenceSolver().solve(quantize_input(inp))
    ok = not res.errors and len(res.claims) <= 1
    return ok, len(res.claims) > 0


def assert_verdicts_match(base, candidate_pods, candidate_node, subsets):
    ev = BatchedConsolidationEvaluator(TPUSolver())
    verdicts = ev.evaluate(base, candidate_pods, candidate_node, subsets)
    assert verdicts is not None, "batched evaluator unexpectedly fell back"
    out = []
    for subset, v in zip(subsets, verdicts):
        seq_ok, seq_repl = sequential_verdict(
            base, candidate_pods, candidate_node, subset
        )
        assert v.ok == seq_ok, (
            f"subset {subset}: batched ok={v.ok} sequential ok={seq_ok}"
        )
        if v.ok:
            # has_replacement feeds the price comparison only for feasible
            # subsets; on rejects its value is not part of the contract
            assert v.has_replacement == seq_repl, (
                f"subset {subset}: batched repl={v.has_replacement} "
                f"sequential repl={seq_repl}"
            )
        out.append((v.ok, v.has_replacement))
    return out


class TestZoneAntiAffinity:
    def _scenario(self, blocker_on_n1: bool):
        # n0 (zone-1a) holds the anti-affinity pod; n1 (zone-1a) is the only
        # other capacity (pool restricted to zone-1a so no replacement claim
        # can dodge the constraint).
        lock = mkpod(
            "lock",
            labels={"svc": "lock"},
            affinity_terms=[
                PodAffinityTerm(
                    label_selector={"svc": "lock"},
                    topology_key=wk.ZONE_LABEL,
                    anti=True,
                )
            ],
        )
        n0 = mknode("n0", "zone-1a", pod_labels=[{"svc": "lock"}])
        n1 = mknode(
            "n1", "zone-1a", pod_labels=[{"svc": "lock"}] if blocker_on_n1 else []
        )
        base = SolverInput(
            pods=[],
            nodes=[n0, n1],
            nodepools=[
                pool(reqs=Requirements.of(
                    Requirement.create(wk.ZONE_LABEL, IN, ["zone-1a"])
                ))
            ],
            zones=ZONES,
        )
        return base, {0: [lock]}, {0: "n0"}

    def test_accept_pod_returns_to_own_zone(self):
        # Removing n0 removes the only svc=lock pod: the re-posed pod founds
        # zone-1a again on n1. Pre-fix the stale count blocked it (reject).
        base, cpods, cnode = self._scenario(blocker_on_n1=False)
        res = assert_verdicts_match(base, cpods, cnode, [[0]])
        assert res[0] == (True, False)

    def test_reject_zone_genuinely_blocked(self):
        # n1 holds its own svc=lock pod: zone-1a is genuinely blocked and the
        # pool offers nowhere else — both paths must reject.
        base, cpods, cnode = self._scenario(blocker_on_n1=True)
        res = assert_verdicts_match(base, cpods, cnode, [[0]])
        assert res[0] == (False, False)


class TestZoneTopologySpread:
    def _scenario(self, n_pods: int):
        tsc = TopologySpreadConstraint(
            max_skew=1, topology_key=wk.ZONE_LABEL, label_selector={"app": "x"}
        )
        spread = [
            mkpod(f"x{i}", labels={"app": "x"}, topology_spread=[tsc])
            for i in range(n_pods)
        ]
        # candidate n0 holds all app=x pods in zone-1a; zones b/c hold one
        # each; n_abs (zone-1a) is the only free capacity (pool zone-1a only)
        n0 = mknode("n0", "zone-1a", pod_labels=[{"app": "x"}] * n_pods)
        nb = mknode("nb", "zone-1b", free_cpu="0", pod_labels=[{"app": "x"}])
        nc = mknode("nc", "zone-1c", free_cpu="0", pod_labels=[{"app": "x"}])
        n_abs = mknode("nabs", "zone-1a")
        base = SolverInput(
            pods=[],
            nodes=[n0, nb, nc, n_abs],
            nodepools=[
                pool(reqs=Requirements.of(
                    Requirement.create(wk.ZONE_LABEL, IN, ["zone-1a"])
                ))
            ],
            zones=ZONES,
        )
        return base, {0: spread}, {0: "n0"}

    def test_accept_counts_rebalance_without_candidate(self):
        # Without n0, zone counts are (0,1,1): both pods legally land on the
        # zone-1a absorber (skew ends at (2,1,1), ≤ maxSkew relative to min
        # count 1). Pre-fix, counts started at (2,1,1) and the pour was
        # blocked (claims in other zones / reject).
        base, cpods, cnode = self._scenario(n_pods=2)
        res = assert_verdicts_match(base, cpods, cnode, [[0]])
        assert res[0] == (True, False)

    def test_reject_skew_blocks_third_pod(self):
        # Four pods, counts start (0,1,1): after two land in zone-1a the
        # counts are (2,1,1) and zone-1a is skew-blocked; the pool offers no
        # other zone — reject on both paths.
        base, cpods, cnode = self._scenario(n_pods=4)
        res = assert_verdicts_match(base, cpods, cnode, [[0]])
        assert res[0][0] is False


class TestMultiNodePrefixes:
    def test_prefixes_match_sequential(self):
        # three candidate nodes in distinct zones, each with one spread pod;
        # big absorber in zone-1a; prefixes [0,1] and [0,1,2] must match the
        # sequential verdicts (mix of accept/reject comes from skew math).
        tsc = TopologySpreadConstraint(
            max_skew=1, topology_key=wk.ZONE_LABEL, label_selector={"app": "y"}
        )
        cpods = {
            i: [mkpod(f"y{i}", labels={"app": "y"}, topology_spread=[tsc])]
            for i in range(3)
        }
        nodes = [
            mknode("c0", "zone-1a", free_cpu="0", pod_labels=[{"app": "y"}]),
            mknode("c1", "zone-1b", free_cpu="0", pod_labels=[{"app": "y"}]),
            mknode("c2", "zone-1c", free_cpu="0", pod_labels=[{"app": "y"}]),
            mknode("nabs", "zone-1a", free_cpu="16"),
        ]
        cnode = {0: "c0", 1: "c1", 2: "c2"}
        base = SolverInput(
            pods=[], nodes=nodes, nodepools=[pool()], zones=ZONES
        )
        assert_verdicts_match(base, cpods, cnode, [[0, 1], [0, 1, 2], [1, 2]])


class TestPositiveHostnameAffinityConsolidation:
    """Kind-2 (positive hostname affinity) on the BATCHED path (VERDICT r4
    missing #3 / next #4): the kernel's bootstrap check reads GLOBAL member
    sums (tot_m_q = Σ node_q_member), so the evaluator must zero removed
    nodes' Q rows per subset — the Q-axis analog of the v_count0 zone
    subtraction — or a consolidated member-hosting node wrongly suppresses
    the bootstrap forever. Both accept and reject asserted differentially.
    Ref: /root/reference/designs/consolidation.md:5-36 (same loop handles
    affinity workloads)."""

    AFF = PodAffinityTerm(label_selector={"svc": "db"},
                          topology_key=wk.HOSTNAME_LABEL, anti=False)

    def test_accept_bootstrap_after_member_host_removed(self):
        # n0 is the candidate AND hosts the only members of svc=db; its own
        # pod owns the kind-2 term. Removing n0 leaves zero members anywhere
        # -> the re-posed pod bootstraps one fresh claim. Without the Q-row
        # zeroing the stale global count suppresses the bootstrap and the
        # only member target is compat-masked -> wrong reject.
        base = SolverInput(
            pods=[],
            nodes=[mknode("n0", "zone-1a", pod_labels=[{"svc": "db"}])],
            nodepools=[pool()], zones=ZONES,
        )
        cand_pods = {0: [mkpod("d0", labels={"svc": "db"},
                               affinity_terms=[self.AFF])]}
        out = assert_verdicts_match(base, cand_pods, {0: "n0"}, [[0]])
        assert out[0][0], "subset should be feasible (bootstrap)"

    def test_accept_colocates_on_surviving_member_host(self):
        # members also live on n1 (not a candidate, has room): the re-posed
        # pod must land on n1 (members present there), no fresh claim.
        base = SolverInput(
            pods=[],
            nodes=[
                mknode("n0", "zone-1a", pod_labels=[{"svc": "db"}]),
                mknode("n1", "zone-1b", pod_labels=[{"svc": "db"}]),
            ],
            nodepools=[pool()], zones=ZONES,
        )
        cand_pods = {0: [mkpod("d0", labels={"svc": "db"},
                               affinity_terms=[self.AFF])]}
        out = assert_verdicts_match(base, cand_pods, {0: "n0"}, [[0]])
        assert out[0][0]
        assert not out[0][1], "should re-pack onto n1, not open a claim"

    def test_reject_member_host_full(self):
        # members survive on n1 but n1 has no room; bootstrap is forbidden
        # (members DO exist) -> infeasible on both paths.
        full = mknode("n1", "zone-1b", free_cpu="100m", free_mem="64Mi",
                      pod_labels=[{"svc": "db"}])
        full.free["pods"] = 0
        base = SolverInput(
            pods=[],
            nodes=[mknode("n0", "zone-1a"), full],
            nodepools=[pool()], zones=ZONES,
        )
        cand_pods = {0: [mkpod("d0", labels={"svc": "db"},
                               affinity_terms=[self.AFF])]}
        out = assert_verdicts_match(base, cand_pods, {0: "n0"}, [[0]])
        assert not out[0][0], "members exist on a full host: must reject"

    def test_multi_candidate_subsets(self):
        # two member-hosting candidates + one plain: removing ALL member
        # hosts flips to bootstrap; removing one keeps co-location on the
        # other. Every subset's verdict must match sequential.
        base = SolverInput(
            pods=[],
            nodes=[
                mknode("n0", "zone-1a", pod_labels=[{"svc": "db"}]),
                mknode("n1", "zone-1b", pod_labels=[{"svc": "db"}]),
                mknode("n2", "zone-1c"),
            ],
            nodepools=[pool()], zones=ZONES,
        )
        cand_pods = {
            0: [mkpod("d0", labels={"svc": "db"}, affinity_terms=[self.AFF])],
            1: [mkpod("d1", labels={"svc": "db"}, affinity_terms=[self.AFF])],
            2: [mkpod("x2")],
        }
        cand_node = {0: "n0", 1: "n1", 2: "n2"}
        assert_verdicts_match(
            base, cand_pods, cand_node, [[0], [1], [2], [0, 1], [0, 1, 2]]
        )


class TestCapacityTypeDomainConsolidation:
    """Differential for the batched evaluator under the CT domain axis
    (round 4): ct-granular sigs no longer set has_topology, so these
    universes take the batched path with the swapped domain — the
    per-subset v_delta subtraction must key on the node's CAPACITY TYPE,
    not its zone."""

    def _scenario(self, spread_blocked: bool):
        # candidate c0 (on-demand) holds a ct-spread member; absorber n1
        # (spot) holds the other. Removing c0 re-poses its member: with
        # maxSkew=1 over {on-demand, spot}, the re-posed pod must be able
        # to land back on on-demand capacity — when the pool is restricted
        # to spot only (spread_blocked), the rebalance is impossible and
        # the subset must be rejected by BOTH paths.
        member = mkpod(
            "m0",
            labels={"tier": "ct"},
            topology_spread=[
                TopologySpreadConstraint(
                    max_skew=1,
                    topology_key=wk.CAPACITY_TYPE_LABEL,
                    label_selector={"tier": "ct"},
                )
            ],
        )
        n0 = mknode("n0", "zone-1a", pod_labels=[{"tier": "ct"}])
        n1 = mknode("n1", "zone-1a", pod_labels=[{"tier": "ct"}, {"tier": "ct"}])
        n1.labels[wk.CAPACITY_TYPE_LABEL] = "spot"
        reqs = None
        if spread_blocked:
            reqs = Requirements.of(
                Requirement.create(wk.CAPACITY_TYPE_LABEL, IN, ["spot"])
            )
        base = SolverInput(
            pods=[], nodes=[n0, n1], nodepools=[pool(reqs=reqs)], zones=ZONES
        )
        return base, {0: [member]}, {0: "n0"}

    def test_ct_delta_keys_on_capacity_type(self):
        base, cpods, cnode = self._scenario(spread_blocked=False)
        assert_verdicts_match(base, cpods, cnode, [[0]])

    def test_ct_spread_reject_matches_sequential(self):
        base, cpods, cnode = self._scenario(spread_blocked=True)
        assert_verdicts_match(base, cpods, cnode, [[0]])


class TestMixedAxisConsolidation:
    """Batched consolidation on a MIXED zone+ct universe (v_axis='mixed'):
    the per-subset v_delta must subtract a removed node's member counts from
    BOTH its zone column and its ct column (batched.py dual-column delta),
    or one axis's verdicts double-count the removed pods."""

    def _base(self, ct_pool_only=None):
        zspread = TopologySpreadConstraint(
            max_skew=1, topology_key=wk.ZONE_LABEL, label_selector={"app": "w"})
        cspread = TopologySpreadConstraint(
            max_skew=1, topology_key=wk.CAPACITY_TYPE_LABEL,
            label_selector={"tier": "ct"})
        zm = mkpod("zm", labels={"app": "w"}, topology_spread=[zspread])
        cm = mkpod("cm", labels={"tier": "ct"}, topology_spread=[cspread])
        # candidate n0 hosts one member of EACH sig; n1/n2 hold the rest
        n0 = mknode("n0", "zone-1a", pod_labels=[{"app": "w"}, {"tier": "ct"}])
        n1 = mknode("n1", "zone-1b", pod_labels=[{"app": "w"}])
        n2 = mknode("n2", "zone-1c", pod_labels=[{"tier": "ct"}])
        n2.labels[wk.CAPACITY_TYPE_LABEL] = "spot"
        reqs = None
        if ct_pool_only:
            reqs = Requirements.of(
                Requirement.create(wk.CAPACITY_TYPE_LABEL, IN, [ct_pool_only]))
        base = SolverInput(
            pods=[], nodes=[n0, n1, n2], nodepools=[pool(reqs=reqs)], zones=ZONES
        )
        return base, {0: [zm, cm]}, {0: "n0"}

    def test_mixed_universe_takes_batched_path_and_matches(self):
        base, cpods, cnode = self._base()
        from karpenter_tpu.solver.backend import TPUSolver

        ev = BatchedConsolidationEvaluator(TPUSolver())
        prep = ev.prepare(base, cpods, cnode)
        assert prep is not None, "mixed universe fell off the batched path"
        assert prep.enc.v_axis == "mixed"
        assert_verdicts_match(base, cpods, cnode, [[0]])

    def test_mixed_universe_reject_matches(self):
        # pool restricted to spot: the re-posed ct member cannot rebalance
        # onto on-demand -> both paths must reject
        base, cpods, cnode = self._base(ct_pool_only="spot")
        assert_verdicts_match(base, cpods, cnode, [[0]])
