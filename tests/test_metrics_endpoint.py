"""Operator endpoint conformance: /metrics exposition format and the
/debug/trace Chrome-trace export.

The /metrics checks pin the Prometheus text-format contract a scraper
relies on: the versioned content-type, HELP/TYPE preceding every
series' samples, one TYPE per series, and sample names that belong to
the declared series (histogram _bucket/_sum/_count included). The
/debug/trace checks pin what Perfetto needs to load the dump: JSON
content-type, a traceEvents list, and complete ("X") events carrying
the solve_id correlation args.
"""

import json
import re
import urllib.error
import urllib.request

import pytest

from karpenter_tpu.metrics.registry import REGISTRY, SOLVER_STAGE_SECONDS
from karpenter_tpu.obs import trace as obstrace
from karpenter_tpu.operator.__main__ import serve_endpoints


@pytest.fixture(scope="module")
def server():
    srv = serve_endpoints(0, 0, enable_profiling=False)
    yield srv.server_address[1]
    srv.shutdown()


def _get(port, path):
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10
        ) as r:
            return r.status, r.headers.get("Content-Type", ""), r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, "", ""


def test_metrics_content_type_and_structure(server):
    SOLVER_STAGE_SECONDS.observe(0.01, stage="backend.encode")  # non-empty
    status, ctype, body = _get(server, "/metrics")
    assert status == 200
    assert ctype == "text/plain; version=0.0.4"
    assert body.endswith("\n")

    help_seen, type_seen, current = set(), {}, None
    for line in body.splitlines():
        if not line:
            continue
        if line.startswith("# HELP "):
            name = line.split()[2]
            help_seen.add(name)
            current = None
        elif line.startswith("# TYPE "):
            _, _, name, kind = line.split(None, 3)
            assert kind in ("counter", "gauge", "histogram"), line
            assert name not in type_seen, f"duplicate TYPE for {name}"
            assert name in help_seen, f"TYPE before HELP for {name}"
            type_seen[name] = kind
            current = name
        else:
            m = re.match(r"([a-zA-Z_:][a-zA-Z0-9_:]*)(\{.*\})? [^ ]+$", line)
            assert m, f"malformed sample line: {line!r}"
            sample = m.group(1)
            assert current is not None, f"sample before any TYPE: {line!r}"
            if type_seen[current] == "histogram":
                assert sample in (
                    current + "_bucket", current + "_sum", current + "_count"
                ), f"sample {sample} outside histogram {current}"
            else:
                assert sample == current, (
                    f"sample {sample} under TYPE {current}"
                )
    # every registered series declared a TYPE (samples may be empty, the
    # HELP/TYPE header must not be)
    assert type_seen.keys() == {m.name for m in REGISTRY.metrics}


def test_debug_trace_endpoint_chrome_loadable(server):
    obstrace.configure(enabled=True, ring=16)
    try:
        tr = obstrace.begin("provisioning")
        with obstrace.attached(tr):
            with obstrace.span("pipeline.dispatch"):
                obstrace.annotate(pending_pods=2)
        obstrace.finish(tr, "ok")
        status, ctype, body = _get(server, "/debug/trace?last=5")
        assert status == 200
        assert ctype == "application/json"
        doc = json.loads(body)
        assert isinstance(doc["traceEvents"], list)
        solve = [e for e in doc["traceEvents"]
                 if e.get("name") == "solve"
                 and e["args"]["solve_id"] == tr.solve_id]
        assert solve and solve[0]["ph"] == "X" and solve[0]["dur"] >= 0
        names = {e["name"] for e in doc["traceEvents"]}
        assert "pipeline.dispatch" in names
        assert "thread_name" in names  # Perfetto track metadata
        status, _, _ = _get(server, "/debug/trace?last=bogus")
        assert status == 400
    finally:
        obstrace.configure(enabled=False)


def test_healthz_carries_flight_recorder_summary(server):
    # /healthz is now a worst-of across the health planes; reset the ones
    # this test does not exercise (earlier operator e2e modules arm the
    # module-global recompile detector and leave prewarm coverage short)
    from karpenter_tpu.obs import anomaly as obsanomaly
    from karpenter_tpu.obs import telemetry as obstelemetry

    obstelemetry.configure()
    obsanomaly.configure()
    status, ctype, body = _get(server, "/healthz")
    assert status == 200 and ctype == "application/json"
    out = json.loads(body)
    assert out["status"] == "ok"
    assert "flight_recorder" in out
