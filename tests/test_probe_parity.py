"""Batched speculative probes vs sequential binary search: decision parity.

ISSUE 4 correctness bar: the batched probe frontier must be decision-for-
decision identical to the sequential binary search — same best prefix at the
search level, same executed Command (candidates AND replacement) at the
controller level, no NodeClaims leaked by probes — while collapsing O(log n)
sequential device round-trips into 1-2 batched dispatches.

Two layers:
  1. search-function parity: speculative_binary_search replayed against
     randomized verdict tables (monotone and adversarially non-monotone)
     must return exactly what the sequential loop returns, in <=2 batches
     whenever the fleet fits probe_batch_max semantics.
  2. controller parity: randomized fleets evaluated by _multi_batched vs
     the forced-sequential path on IDENTICAL cluster state produce the same
     multi-consolidation command, covering delete-only (budget-clamped),
     replacement (require_cheaper satisfied), and no-command outcomes.
"""

import random

import pytest

from karpenter_tpu.api import wellknown as wk
from karpenter_tpu.api.objects import (
    Budget,
    Disruption,
    NodeClaimTemplate,
    NodePool,
    ObjectMeta,
    Pod,
    TopologySpreadConstraint,
)
from karpenter_tpu.controllers import store as st
from karpenter_tpu.disruption.batched import (
    binary_probe_frontier,
    speculative_binary_search,
)
from karpenter_tpu.disruption.controller import DisruptionController
from karpenter_tpu.operator.operator import new_kwok_operator
from karpenter_tpu.solver.backend import TPUSolver
from karpenter_tpu.utils.resources import Resources

from tests.test_e2e_kwok import FakeClock

# ------------------------------------------------------- search-level parity


def _sequential_best(verdict, lo, hi):
    """The exact loop _evaluate runs on the sequential path."""
    best, probes = None, 0
    while lo <= hi:
        mid = (lo + hi) // 2
        probes += 1
        if verdict(mid):
            best = mid
            lo = mid + 1
        else:
            hi = mid - 1
    return best, probes


def test_frontier_enumerates_decision_tree_levels():
    # top two levels of the [1,7] decision tree: mid 4, then 2 and 6
    assert binary_probe_frontier(1, 7, 2) == [2, 4, 6]
    assert binary_probe_frontier(1, 7, 1) == [4]
    # degenerate interval
    assert binary_probe_frontier(3, 3, 4) == [3]
    # levels deeper than the tree just enumerate the whole interval
    assert binary_probe_frontier(1, 7, 10) == [1, 2, 3, 4, 5, 6, 7]


@pytest.mark.parametrize("seed", range(6))
def test_search_parity_random_tables(seed):
    rng = random.Random(seed)
    for _ in range(30):
        n = rng.randint(2, 400)
        if rng.random() < 0.5:
            cut = rng.randint(1, n + 1)  # monotone: feasible up to `cut`
            table = {k: k <= cut for k in range(2, n + 1)}
        else:
            p = rng.choice((0.2, 0.5, 0.8))  # adversarial: non-monotone
            table = {k: rng.random() < p for k in range(2, n + 1)}
        for pbm in (1, 2, 7, 64, 512):
            best, probed, batches = speculative_binary_search(
                (lambda ks: [table[k] for k in ks]),
                2, n, (lambda k, v: bool(v)), probe_batch_max=pbm,
            )
            seq_best, _ = _sequential_best(lambda k: table[k], 2, n)
            assert best == seq_best, (
                f"n={n} pbm={pbm}: speculative {best} != sequential {seq_best}"
            )
            # every replayed decision consulted a genuinely probed verdict
            for k, v in probed.items():
                assert v == table[k]
            if n - 1 <= pbm:
                assert batches <= 1, "interval fits one batch"


@pytest.mark.parametrize("n", [1_000, 50_000, 200_000])
def test_large_fleets_resolve_in_two_dispatches(n):
    rng = random.Random(n)
    cut = rng.randint(2, n)
    tables = [
        lambda k: k <= cut,                       # monotone
        lambda k: (k * 2654435761) % 97 < 48,     # deterministic pseudo-noise
    ]
    for verdict in tables:
        best, _probed, batches = speculative_binary_search(
            (lambda ks: [verdict(k) for k in ks]),
            2, n, (lambda k, v: bool(v)), probe_batch_max=512,
        )
        seq_best, seq_probes = _sequential_best(verdict, 2, n)
        assert best == seq_best
        assert batches <= 2, f"n={n}: {batches} dispatches (sequential: {seq_probes})"
        assert seq_probes >= 6  # the round-trips the batching collapses


# ---------------------------------------------------- controller-level parity


def _mk_operator(budget="100%"):
    clock = FakeClock()
    op = new_kwok_operator(clock=clock, solver=TPUSolver())
    op.clock = clock
    op.store.create(
        st.NODEPOOLS,
        NodePool(
            meta=ObjectMeta(name="default"),
            template=NodeClaimTemplate(),
            disruption=Disruption(
                consolidation_policy="WhenEmptyOrUnderutilized",
                consolidate_after_s=0.0,
                budgets=[Budget(nodes=budget)],
            ),
        ),
    )
    return op


def _fanout(op, specs):
    """One pod per node via hostname spread, then drop the constraint so the
    fleet becomes consolidatable (the config-5 construction, miniaturized)."""
    tsc = TopologySpreadConstraint(
        max_skew=1, topology_key=wk.HOSTNAME_LABEL, label_selector={"app": "wide"}
    )
    for name, cpu, mem in specs:
        op.store.create(
            st.PODS,
            Pod(
                meta=ObjectMeta(name=name, uid=name, labels={"app": "wide"}),
                requests=Resources.parse({"cpu": cpu, "memory": mem}),
                topology_spread=[tsc],
            ),
        )
    op.manager.settle(max_ticks=600)
    assert len(op.store.list(st.NODES)) == len(specs), "spread must fan out"
    for name, _cpu, _mem in specs:
        p = op.store.get(st.PODS, name)
        p.topology_spread = []
        op.store.update(st.PODS, p)
    op.clock.advance(30)


def _controller(op) -> DisruptionController:
    return next(
        c for c in op.manager.controllers if isinstance(c, DisruptionController)
    )


def _fingerprint(dc, candidates, budgets):
    """Evaluate multi-consolidation WITHOUT touching the store: replacement
    creation is stubbed to record the ClaimResult, so batched and sequential
    runs see identical cluster state."""
    created = []
    dc._create_replacement = lambda cr: (created.append(cr), f"r{len(created)}")[1]
    cmd = dc._evaluate("multi-consolidation", list(candidates), budgets)
    if cmd is None:
        return None
    return (
        cmd.method,
        tuple(c.claim.name for c in cmd.candidates),
        len(cmd.replacement_names),
        tuple((cr.nodepool, tuple(sorted(cr.instance_type_names))) for cr in created),
    )


def _parity_check(op):
    """Batched vs forced-sequential command on identical state; returns the
    batched fingerprint (None = no command on either path)."""
    dc = _controller(op)
    candidates = dc._candidates()
    assert len(candidates) >= 2
    budgets = dc._budget_allowance(candidates)
    decisions0 = dc.stats.get("probe_decisions", 0)
    dispatches0 = dc.stats.get("probe_dispatches", 0)
    fp_batched = _fingerprint(dc, candidates, budgets)
    if fp_batched is not None:
        # the whole decision fit the speculative frontier: 1 probe dispatch,
        # 2 at most (ISSUE 4 acceptance: <=2 where sequential needs O(log n))
        assert dc.stats.get("probe_decisions", 0) - decisions0 == 1
        assert dc.stats.get("probe_dispatches", 0) - dispatches0 <= 2
    dc._batched = None  # force the sequential binary search
    dc._solve_service = None
    dc._prep_cache = None
    fp_seq = _fingerprint(dc, candidates, budgets)
    assert fp_batched == fp_seq, (
        f"batched {fp_batched} != sequential {fp_seq}"
    )
    return fp_batched


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_randomized_fleets_batched_equals_sequential(seed):
    rng = random.Random(seed)
    n = rng.randint(6, 11)
    specs = [
        (f"p{i:02d}", rng.choice(("100m", "150m", "250m", "300m")), "192Mi")
        for i in range(n)
    ]
    op = _mk_operator()
    _fanout(op, specs)
    _parity_check(op)


def test_replacement_branch_full_collapse():
    """Identical small pods, 100% budget: the fleet collapses onto ONE
    cheaper replacement (require_cheaper + allow_replacement branch)."""
    op = _mk_operator(budget="100%")
    _fanout(op, [(f"w{i}", "150m", "192Mi") for i in range(8)])
    fp = _parity_check(op)
    assert fp is not None
    method, cand_names, n_repl, repls = fp
    assert len(cand_names) == 8 and n_repl == 1
    assert repls[0][0] == "default"


def test_delete_only_branch_budget_clamped():
    """A nodes=3 budget clamps the prefix: 3 nodes delete, their pods absorb
    onto remaining headroom, NO replacement — and out-of-budget prefixes are
    answered host-side identically on both paths."""
    op = _mk_operator(budget="3")
    _fanout(op, [(f"w{i}", "150m", "192Mi") for i in range(8)])
    fp = _parity_check(op)
    assert fp is not None
    method, cand_names, n_repl, _repls = fp
    assert len(cand_names) == 3, "budget must clamp the accepted prefix"
    assert n_repl == 0, "absorbed onto surviving nodes: delete-only"


def test_probes_leak_no_nodeclaims():
    """The real (unstubbed) batched evaluation: the only NodeClaim created is
    the executed command's replacement — probe rows never materialize one."""
    op = _mk_operator(budget="100%")
    _fanout(op, [(f"w{i}", "150m", "192Mi") for i in range(8)])
    dc = _controller(op)
    candidates = dc._candidates()
    budgets = dc._budget_allowance(candidates)
    before = len(op.store.list(st.NODECLAIMS))
    cmd = dc._evaluate("multi-consolidation", candidates, budgets)
    assert cmd is not None and len(cmd.candidates) >= 2
    after = len(op.store.list(st.NODECLAIMS))
    assert after == before + len(cmd.replacement_names), (
        "speculative probes must not leak NodeClaims"
    )
