"""Static metrics-drift check: code and registry cannot diverge silently.

Two directions:
- every `karpenter_*` series literal mentioned anywhere in the package
  must be a REGISTERED series (or a documented allowance) — a typo'd or
  renamed metric name in a log line, docstring, or dashboard hint rots
  quietly otherwise;
- every registered series must be REFERENCED outside registry.py — a
  metric nobody sets/increments is a dead series that dashboards will
  chart as flatlines forever (the bug class that left
  karpenter_cluster_state_node_count dark for five PRs).
"""

import pathlib
import re

from karpenter_tpu.metrics import registry as reg

PKG = pathlib.Path(reg.__file__).resolve().parents[1]  # karpenter_tpu/
LITERAL = re.compile(r"\bkarpenter_[a-z0-9_]+\b")

# Non-series mentions the literal scan is allowed to hit:
ALLOWED = {
    # the package's own name (logger names, module docstrings)
    "karpenter_tpu",
    # reference metric we intentionally do NOT export: the in-process
    # store is synced by construction (state/cluster.py module docstring)
    "karpenter_cluster_state_synced",
}
# exposition-format suffixes a literal may carry on a registered base name
SUFFIXES = ("_bucket", "_sum", "_count", "_total")


def _package_sources():
    for p in sorted(PKG.rglob("*.py")):
        if p.name == "registry.py":
            continue
        yield p, p.read_text()


def test_every_metric_literal_is_registered():
    registered = {m.name for m in reg.REGISTRY.metrics}
    bad = []
    for path, src in _package_sources():
        for lit in set(LITERAL.findall(src)):
            if lit in registered or lit in ALLOWED:
                continue
            base = next((lit[: -len(s)] for s in SUFFIXES
                         if lit.endswith(s) and lit[: -len(s)] in registered),
                        None)
            if base is not None:
                continue
            # doc-style prefix mention ("karpenter_tpu_solver_upload_*")
            if lit.endswith("_") and any(n.startswith(lit) for n in registered):
                continue
            bad.append(f"{path.relative_to(PKG.parent)}: {lit}")
    assert not bad, "unregistered metric literals:\n" + "\n".join(bad)


def test_no_dead_series():
    """Every registered metric's binding name appears in at least one
    module outside registry.py (the code references metrics through the
    registry's module-level bindings, so a binding nobody imports is a
    series nobody feeds)."""
    bindings = {
        var: m.name
        for var, m in vars(reg).items()
        if isinstance(m, reg._Metric)
    }
    # every registered metric object must have a module-level binding —
    # an anonymous registration would be invisible to this check
    bound = set(id(m) for m in vars(reg).values() if isinstance(m, reg._Metric))
    unbound = [m.name for m in reg.REGISTRY.metrics if id(m) not in bound]
    assert not unbound, f"registered without a module binding: {unbound}"

    corpus = "\n".join(src for _, src in _package_sources())
    dead = [f"{var} ({name})" for var, name in bindings.items()
            if var not in corpus]
    assert not dead, "dead series (registered, never referenced):\n" + "\n".join(dead)


def test_registered_names_unique():
    names = [m.name for m in reg.REGISTRY.metrics]
    assert len(names) == len(set(names)), "duplicate series registered"


def test_slo_and_meter_series_are_registered():
    """ISSUE 12 acceptance: the SLO burn-rate gauges and the per-tenant
    meters are part of the /metrics contract — their exact names are what
    dashboards and billing scrape, so pin them."""
    registered = {m.name for m in reg.REGISTRY.metrics}
    for name in (
        "karpenter_slo_burn_rate",
        "karpenter_slo_breaches_total",
        "karpenter_tenant_meter_solves_total",
        "karpenter_tenant_meter_device_ms_total",
        "karpenter_tenant_meter_h2d_bytes_total",
        "karpenter_tenant_meter_d2h_bytes_total",
        "karpenter_solver_explain_records_total",
        "karpenter_solver_explain_wide_total",
        "karpenter_solver_explain_bytes_per_solve",
    ):
        assert name in registered, f"{name} missing from the registry"


def test_streaming_series_are_registered():
    """ISSUE 13 acceptance: the streaming delta-solve series are part of the
    /metrics contract — applied batches/events, reason-labeled re-baselines,
    journal depth, and resident-state age are what the soak dashboards and
    the re-baseline alert scrape, so pin their exact names."""
    registered = {m.name for m in reg.REGISTRY.metrics}
    for name in (
        "karpenter_streaming_batches_applied_total",
        "karpenter_streaming_events_applied_total",
        "karpenter_streaming_rebaseline_total",
        "karpenter_streaming_journal_depth",
        "karpenter_streaming_resident_state_age_seconds",
    ):
        assert name in registered, f"{name} missing from the registry"


def test_cohort_series_are_registered():
    """ISSUE 16 acceptance: the fused-cohort dispatch series are part of
    the /metrics contract — cohort width, fused-launch count, and the
    per-tenant poison-replay counter are what the fusion dashboards and
    the fairness alerts scrape, so pin their exact names."""
    registered = {m.name for m in reg.REGISTRY.metrics}
    for name in (
        "karpenter_solver_cohort_size",
        "karpenter_solver_fused_dispatches_total",
        "karpenter_solver_cohort_poison_replays_total",
    ):
        assert name in registered, f"{name} missing from the registry"


def test_vault_series_are_registered():
    """ISSUE 17 acceptance: the solver-vault series are part of the
    /metrics contract — snapshot latency/size/age, restore latency, and
    the restore/failure counters are what the durability dashboards and
    the vault-staleness alert scrape, so pin their exact names."""
    registered = {m.name for m in reg.REGISTRY.metrics}
    for name in (
        "karpenter_solver_vault_snapshot_seconds",
        "karpenter_solver_vault_bytes",
        "karpenter_solver_vault_age_seconds",
        "karpenter_solver_vault_restore_seconds",
        "karpenter_solver_vault_restores_total",
        "karpenter_solver_vault_restore_failures_total",
    ):
        assert name in registered, f"{name} missing from the registry"


def test_federation_series_are_registered():
    """ISSUE 18 acceptance: the federation series are part of the /metrics
    contract — healthy-host count, tenant re-homings, journal replication
    lag, and cross-host failovers are what the federation dashboards and
    the host-loss alert scrape, so pin their exact names. The existing
    fleet series additionally carry a per-host label under federation;
    empty host labels must keep single-host series identity unchanged."""
    registered = {m.name for m in reg.REGISTRY.metrics}
    for name in (
        "karpenter_federation_hosts_healthy",
        "karpenter_federation_tenant_moves_total",
        "karpenter_federation_journal_replication_lag",
        "karpenter_federation_cross_host_failovers_total",
    ):
        assert name in registered, f"{name} missing from the registry"
    by_name = {m.name: m for m in reg.REGISTRY.metrics}
    for name in (
        "karpenter_solver_fleet_healthy",
        "karpenter_solver_failover_total",
        "karpenter_solver_requeued_solves_total",
        "karpenter_solver_canary_latency_seconds",
    ):
        assert "host" in by_name[name].label_names, (
            f"{name} lost its federation host label"
        )


def test_convex_series_are_registered():
    """ISSUE 19 acceptance: the convex-backend series are part of the
    /metrics contract — solve/fallback counters (fallbacks carry the
    reason label the loud-fallback alert keys on) and the per-solve
    iteration histogram are what the quality dashboards scrape, so pin
    their exact names."""
    registered = {m.name for m in reg.REGISTRY.metrics}
    for name in (
        "karpenter_solver_convex_solves_total",
        "karpenter_solver_convex_fallbacks_total",
        "karpenter_solver_convex_iterations",
    ):
        assert name in registered, f"{name} missing from the registry"
    by_name = {m.name: m for m in reg.REGISTRY.metrics}
    assert "reason" in by_name[
        "karpenter_solver_convex_fallbacks_total"
    ].label_names, "convex fallbacks lost their reason label"


def test_sharded_fallback_reason_label():
    """ISSUE 20 acceptance: the sharded-fallback counter grew a {reason}
    label (tiny_fleet / no_mesh live; v_axis / q_axis reserved — nothing
    emits them since the sparse-constraint lift). Alerts key on the label,
    so its presence is part of the /metrics contract."""
    by_name = {m.name: m for m in reg.REGISTRY.metrics}
    m = by_name.get("karpenter_solver_sharded_fallback_total")
    assert m is not None, "sharded fallback counter missing"
    assert "reason" in m.label_names, (
        "sharded fallbacks lost their reason label"
    )


def test_every_reason_code_has_name_and_spec_row():
    """Every kernel reason code must have a decoder-side name AND a SPEC.md
    row — an undocumented code is a wire symbol operators cannot read."""
    from karpenter_tpu.obs.explain import REASON_NAMES
    from karpenter_tpu.solver.tpu.ffd import EXPLAIN_REASONS

    spec = (PKG / "solver" / "SPEC.md").read_text()
    for name, code in EXPLAIN_REASONS:
        assert REASON_NAMES.get(code) == name, (
            f"reason {code} ({name}) missing/misnamed in obs/explain.REASON_NAMES"
        )
        assert re.search(rf"\|\s*`?{code}`?\s*\|\s*`{name}`", spec), (
            f"reason {code} ({name}) has no SPEC.md table row"
        )


def test_health_plane_series_are_registered():
    """ISSUE 14 acceptance: the runtime health plane's series are part of
    the /metrics contract — compile/recompile counts, AOT prewarm coverage,
    arena byte accounting + evictions, HBM watermarks, and the anomaly
    detector's trip state are what the recompile alert and the memory
    dashboards scrape, so pin their exact names."""
    registered = {m.name for m in reg.REGISTRY.metrics}
    for name in (
        "karpenter_solver_compiles_total",
        "karpenter_solver_compile_seconds",
        "karpenter_solver_prewarm_coverage",
        "karpenter_solver_prewarm_failures_total",
        "karpenter_solver_arena_bytes",
        "karpenter_solver_arena_evictions_total",
        "karpenter_solver_hbm_bytes",
        "karpenter_solver_perf_anomalies_total",
        "karpenter_solver_perf_anomaly_state",
    ):
        assert name in registered, f"{name} missing from the registry"
