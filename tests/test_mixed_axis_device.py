"""Mixed zone+ct domain constraints in ONE device solve.

Round-4 verdict #2: a solve mixing zone-granular and capacity-type-granular
sigs fell back whole-solve (encode routed it off-device); production surges
mix them routinely (one ct-spread deployment amid zone-TSC workloads), which
silently degraded a 50k-pod solve to interpreter speed. The engine is
domain-generic, so both axes now run concatenated on the domain axis with
per-group axis binding — these tests pin bit-identical parity with the
oracle AND that the solve stays on device. Reference semantics: all three
topology keys are first-class together
(/root/reference/website/content/en/preview/concepts/scheduling.md:383-429).

Pods genuinely constrained on BOTH axes (one pod owning a zone TSC and a ct
spread, or zone-constrained while a ct anti selects it) stay fallback —
parity still holds through the oracle, asserted with expect_device=False.
"""

import random

import pytest

from karpenter_tpu.api import wellknown as wk
from karpenter_tpu.api.objects import PodAffinityTerm, TopologySpreadConstraint
from karpenter_tpu.scheduling.requirements import IN, Requirement, Requirements
from karpenter_tpu.provisioning.scheduler import SolverInput

from tests.test_zone_device import (
    ZONES,
    assert_zone_parity,
    mknode,
    mkpod,
    pool,
)

CTS = ("on-demand", "spot")


def ct_pool(name="default", weight=0):
    """Pool admitting both capacity types (the ct domain universe)."""
    return pool(name, weight=weight)


def ztsc(sel, skew=1):
    return TopologySpreadConstraint(
        max_skew=skew, topology_key=wk.ZONE_LABEL, label_selector=sel
    )


def ctsc(sel, skew=1):
    return TopologySpreadConstraint(
        max_skew=skew, topology_key=wk.CAPACITY_TYPE_LABEL, label_selector=sel
    )


def mkinp(pods, nodes=()):
    return SolverInput(
        pods=pods, nodes=list(nodes), nodepools=[ct_pool()], zones=ZONES,
        capacity_types=CTS,
    )


def ct_node(name, zone, ct, matching=0, sel=None):
    n = mknode(name, zone, matching=matching, sel=sel)
    n.labels[wk.CAPACITY_TYPE_LABEL] = ct
    return n


class TestMixedAxisOnDevice:
    def test_zone_tsc_plus_ct_tsc_fresh(self):
        pods = [
            mkpod(f"z{i}", cpu="2", mem="4Gi", labels={"app": "w"},
                  topology_spread=[ztsc({"app": "w"})])
            for i in range(6)
        ] + [
            mkpod(f"c{i}", cpu="1", mem="2Gi", labels={"tier": "ct"},
                  topology_spread=[ctsc({"tier": "ct"})])
            for i in range(4)
        ]
        assert_zone_parity(mkinp(pods))

    def test_one_ct_pod_does_not_poison_zone_solve(self):
        """The VERDICT's cliff shape: one ct-spread pod amid a zone-TSC
        workload must keep the WHOLE solve on device."""
        pods = [
            mkpod(f"z{i:02d}", labels={"app": "w"}, topology_spread=[ztsc({"app": "w"})])
            for i in range(24)
        ]
        pods.append(
            mkpod("ct0", labels={"tier": "x"}, topology_spread=[ctsc({"tier": "x"})])
        )
        assert_zone_parity(mkinp(pods))

    def test_zone_affinity_plus_ct_spread(self):
        pods = [
            mkpod(f"a{i}", labels={"svc": "db"},
                  affinity_terms=[PodAffinityTerm(
                      label_selector={"svc": "db"}, topology_key=wk.ZONE_LABEL,
                      anti=False)])
            for i in range(5)
        ] + [
            mkpod(f"c{i}", labels={"tier": "ct"},
                  topology_spread=[ctsc({"tier": "ct"}, skew=2)])
            for i in range(6)
        ]
        assert_zone_parity(mkinp(pods))

    def test_ct_anti_plus_zone_tsc(self):
        pods = [
            mkpod(f"z{i}", labels={"app": "w"}, topology_spread=[ztsc({"app": "w"})])
            for i in range(6)
        ] + [
            mkpod(f"l{i}", labels={"lock": f"k{i}"},
                  affinity_terms=[PodAffinityTerm(
                      label_selector={"lock": f"k{i}"},
                      topology_key=wk.CAPACITY_TYPE_LABEL, anti=True)])
            for i in range(2)
        ]
        assert_zone_parity(mkinp(pods))

    def test_mixed_with_existing_nodes(self):
        nodes = [
            ct_node("n-a", "zone-1a", "on-demand", matching=2, sel={"app": "w"}),
            ct_node("n-b", "zone-1b", "spot", matching=1, sel={"app": "w"}),
            ct_node("n-c", "zone-1c", "on-demand"),
        ]
        pods = [
            mkpod(f"z{i}", labels={"app": "w"}, topology_spread=[ztsc({"app": "w"})])
            for i in range(7)
        ] + [
            mkpod(f"c{i}", labels={"app": "w"},  # cross-axis MEMBERSHIP:
                  # these own a ct sig whose selector also matches the
                  # zone-TSC pods (and vice versa) — counts must record on
                  # both axes wherever the target's domain is determined
                  topology_spread=[ctsc({"app": "w"}, skew=2)])
            for i in range(4)
        ]
        assert_zone_parity(mkinp(pods, nodes))

    def test_two_axis_pod_falls_back_with_parity(self):
        pods = [
            mkpod("both", labels={"app": "w"},
                  topology_spread=[ztsc({"app": "w"}), ctsc({"app": "w"})])
        ] + [
            mkpod(f"z{i}", labels={"app": "w"}, topology_spread=[ztsc({"app": "w"})])
            for i in range(4)
        ]
        assert_zone_parity(mkinp(pods), expect_device=False)

    def test_zone_constrained_pod_selected_by_ct_anti_falls_back(self):
        pods = [
            mkpod("z0", labels={"app": "w", "pick": "me"},
                  topology_spread=[ztsc({"app": "w"})]),
            mkpod("anti", labels={"other": "1"},
                  affinity_terms=[PodAffinityTerm(
                      label_selector={"pick": "me"},
                      topology_key=wk.CAPACITY_TYPE_LABEL, anti=True)]),
        ]
        assert_zone_parity(mkinp(pods), expect_device=False)


@pytest.mark.parametrize("seed", range(10))
def test_mixed_axis_fuzz(seed):
    """Random mixes of zone-TSC / ct-TSC / zone-aff / ct-anti pods plus
    existing nodes; single-axis-per-pod mixes must stay on device."""
    rng = random.Random(3000 + seed)
    pods = []
    for i in range(rng.randrange(8, 26)):
        kind = rng.random()
        name = f"p{i:03d}"
        if kind < 0.35:
            pods.append(mkpod(name, labels={"app": "w"},
                              topology_spread=[ztsc({"app": "w"})]))
        elif kind < 0.6:
            pods.append(mkpod(name, labels={"tier": "ct"},
                              topology_spread=[ctsc({"tier": "ct"},
                                                    skew=rng.choice([1, 2]))]))
        elif kind < 0.75:
            pods.append(mkpod(name, labels={"svc": "db"},
                              affinity_terms=[PodAffinityTerm(
                                  label_selector={"svc": "db"},
                                  topology_key=wk.ZONE_LABEL, anti=False)]))
        elif kind < 0.85:
            pods.append(mkpod(name, labels={"lock": f"k{i % 3}"},
                              affinity_terms=[PodAffinityTerm(
                                  label_selector={"lock": f"k{i % 3}"},
                                  topology_key=wk.CAPACITY_TYPE_LABEL,
                                  anti=True)]))
        else:
            pods.append(mkpod(name, cpu=rng.choice(["500m", "1", "2"])))
    nodes = []
    for j in range(rng.randrange(0, 5)):
        nodes.append(ct_node(
            f"n{j}", rng.choice(ZONES), rng.choice(CTS),
            matching=rng.randrange(0, 3),
            sel=rng.choice([{"app": "w"}, {"tier": "ct"}]),
        ))
    assert_zone_parity(mkinp(pods, nodes))


class TestMixedAxisNative:
    """The C++ core drives BOTH domain axes too (round 5): DD = Z + C
    concatenated columns, per-group axis binding, per-axis count recording
    — 3-way parity (native vs oracle) over the same mixed families the
    device tests pin."""

    @staticmethod
    def _native_parity(inp):
        from karpenter_tpu.solver.backend import ReferenceSolver, quantize_input
        from karpenter_tpu.solver.native import NativeSolver

        ns = NativeSolver()
        out = ns.solve(inp)
        ref = ReferenceSolver().solve(quantize_input(inp))
        assert set(out.errors) == set(ref.errors)
        assert out.placements == ref.placements, {
            k: (out.placements.get(k), ref.placements.get(k))
            for k in set(out.placements) | set(ref.placements)
            if out.placements.get(k) != ref.placements.get(k)
        }
        assert len(out.claims) == len(ref.claims)
        for rc, tc in zip(ref.claims, out.claims):
            assert sorted(rc.instance_type_names) == sorted(tc.instance_type_names)
            assert rc.pod_uids == tc.pod_uids
        assert ns.stats["native_solves"] == 1, ns.stats
        return out

    def test_zone_tsc_plus_ct_tsc(self):
        pods = [
            mkpod(f"z{i}", cpu="2", mem="4Gi", labels={"app": "w"},
                  topology_spread=[ztsc({"app": "w"})])
            for i in range(6)
        ] + [
            mkpod(f"c{i}", cpu="1", mem="2Gi", labels={"tier": "ct"},
                  topology_spread=[ctsc({"tier": "ct"})])
            for i in range(4)
        ]
        self._native_parity(mkinp(pods))

    def test_ct_anti_plus_zone_affinity(self):
        pods = [
            mkpod(f"a{i}", labels={"svc": "db"},
                  affinity_terms=[PodAffinityTerm(
                      label_selector={"svc": "db"}, topology_key=wk.ZONE_LABEL,
                      anti=False)])
            for i in range(4)
        ] + [
            mkpod(f"l{i}", labels={"lock": f"k{i}"},
                  affinity_terms=[PodAffinityTerm(
                      label_selector={"lock": f"k{i}"},
                      topology_key=wk.CAPACITY_TYPE_LABEL, anti=True)])
            for i in range(2)
        ]
        self._native_parity(mkinp(pods))

    def test_mixed_with_existing_nodes_cross_membership(self):
        nodes = [
            ct_node("n-a", "zone-1a", "on-demand", matching=2, sel={"app": "w"}),
            ct_node("n-b", "zone-1b", "spot", matching=1, sel={"app": "w"}),
            ct_node("n-c", "zone-1c", "on-demand"),
        ]
        pods = [
            mkpod(f"z{i}", labels={"app": "w"}, topology_spread=[ztsc({"app": "w"})])
            for i in range(7)
        ] + [
            mkpod(f"c{i}", labels={"app": "w"},
                  topology_spread=[ctsc({"app": "w"}, skew=2)])
            for i in range(4)
        ]
        self._native_parity(mkinp(pods, nodes))

    @pytest.mark.parametrize("seed", range(6))
    def test_native_mixed_fuzz(self, seed):
        rng = random.Random(5000 + seed)
        pods = []
        for i in range(rng.randrange(6, 20)):
            kind = rng.random()
            name = f"p{i:03d}"
            if kind < 0.35:
                pods.append(mkpod(name, labels={"app": "w"},
                                  topology_spread=[ztsc({"app": "w"})]))
            elif kind < 0.6:
                pods.append(mkpod(name, labels={"tier": "ct"},
                                  topology_spread=[ctsc({"tier": "ct"},
                                                        skew=rng.choice([1, 2]))]))
            elif kind < 0.75:
                pods.append(mkpod(name, labels={"svc": "db"},
                                  affinity_terms=[PodAffinityTerm(
                                      label_selector={"svc": "db"},
                                      topology_key=wk.CAPACITY_TYPE_LABEL,
                                      anti=False)]))
            else:
                pods.append(mkpod(name, cpu=rng.choice(["500m", "1", "2"])))
        nodes = []
        for j in range(rng.randrange(0, 4)):
            nodes.append(ct_node(
                f"n{j}", rng.choice(ZONES), rng.choice(CTS),
                matching=rng.randrange(0, 3),
                sel=rng.choice([{"app": "w"}, {"tier": "ct"}]),
            ))
        self._native_parity(mkinp(pods, nodes))
