"""Chaos loop: randomized churn through the FULL control plane with
invariants checked every step — the fault-injection discipline of the
reference's e2e suites (interruption, consolidation, GC) compressed into a
hermetic, seeded, deterministic run.

Actions per step: create pods (plain / zone-spread / ct-spread / hostname-
affinity), delete pods, spot-interrupt random instances, kill instances
out from under their nodes (node-killer territory), advance the clock.

Invariants (every step): a bound pod's node exists; no two pods bound to
phantom capacity (node allocatable never oversubscribed); instances
without claims are reaped within the GC grace; the loop converges at the
end with every surviving pod bound.
"""

import random

import pytest

from karpenter_tpu.api import wellknown as wk
from karpenter_tpu.api.objects import (
    ObjectMeta,
    Pod,
    PodAffinityTerm,
    TopologySpreadConstraint,
)
from karpenter_tpu.controllers import store as st
from karpenter_tpu.controllers.interruption import SPOT_INTERRUPTION, Message
from karpenter_tpu.operator.operator import new_kwok_operator
from karpenter_tpu.utils.resources import CPU, PODS, Resources

from tests.test_e2e_kwok import FakeClock, mkpool


def _mkpod(rng, i):
    name = f"x{i:04d}"
    cpu = rng.choice(["100m", "250m", "500m", "1"])
    p = Pod(
        meta=ObjectMeta(name=name, uid=name),
        requests=Resources.parse({"cpu": cpu, "memory": "256Mi"}),
    )
    r = rng.random()
    if r < 0.15:
        p.meta.labels["app"] = "zs"
        p.topology_spread = [TopologySpreadConstraint(
            max_skew=1, topology_key=wk.ZONE_LABEL, label_selector={"app": "zs"})]
    elif r < 0.25:
        p.meta.labels["tier"] = "ct"
        p.topology_spread = [TopologySpreadConstraint(
            max_skew=2, topology_key=wk.CAPACITY_TYPE_LABEL,
            label_selector={"tier": "ct"})]
    elif r < 0.33:
        p.meta.labels["svc"] = "db"
        p.affinity_terms = [PodAffinityTerm(
            label_selector={"svc": "db"}, topology_key=wk.HOSTNAME_LABEL,
            anti=False)]
    elif r < 0.40:
        # zone anti-affinity singleton lock (unique key per pod: each is
        # its own group, one per zone at most)
        p.meta.labels["lock"] = f"l{i % 5}"
        p.affinity_terms = [PodAffinityTerm(
            label_selector={"lock": f"l{i % 5}"},
            topology_key=wk.ZONE_LABEL, anti=True)]
    elif r < 0.48:
        p.node_selector = {
            wk.ZONE_LABEL: rng.choice(("zone-1a", "zone-1b", "zone-1c"))
        }
    return p


def _check_invariants(op, step):
    nodes = {n.meta.name: n for n in op.store.list(st.NODES)}
    for p in op.store.list(st.PODS):
        if p.node_name:
            assert p.node_name in nodes, (
                f"step {step}: pod {p.meta.name} bound to vanished node "
                f"{p.node_name}"
            )
    # allocatable never oversubscribed (cpu + pod slots)
    for n in nodes.values():
        bound = [p for p in op.store.list(st.PODS) if p.node_name == n.meta.name]
        used_cpu = sum(int(p.requests.get_(CPU)) for p in bound)
        assert used_cpu <= int(n.allocatable.get_(CPU)), (
            f"step {step}: node {n.meta.name} cpu oversubscribed"
        )
        cap_pods = int(n.allocatable.get_(PODS) or 0)
        if cap_pods:
            assert len(bound) <= cap_pods, f"step {step}: pod slots oversubscribed"


def _assert_converged(op):
    """Every surviving pod bound, and every instance owned by a live claim
    (no leaks). Two classes are legitimately Pending, as in kube: positive
    hostname affinity whose co-location node is full, and anti-affinity
    groups that exhausted their domains (3 zones -> at most 3 pods per
    anti lock)."""
    pods = [p for p in op.store.list(st.PODS) if not p.meta.deleting]
    stuck = [
        p.meta.name
        for p in pods
        if not p.node_name and not any(
            (a.topology_key == wk.HOSTNAME_LABEL and not a.anti) or a.anti
            for a in p.affinity_terms
        )
    ]
    assert not stuck, f"unconverged pods after settle: {stuck}"
    claim_ids = {
        c.provider_id.rsplit("/", 1)[-1]
        for c in op.store.list(st.NODECLAIMS)
        if c.provider_id
    }
    leaked = [x.id for x in op.cloud.describe_instances() if x.id not in claim_ids]
    assert not leaked, f"leaked instances: {leaked}"


@pytest.mark.parametrize("seed", range(4))
def test_chaos_churn_converges(seed):
    rng = random.Random(1000 + seed)
    clock = FakeClock()
    op = new_kwok_operator(clock=clock)
    op.store.create(st.NODEPOOLS, mkpool())
    i = 0
    for step in range(60):
        action = rng.random()
        if action < 0.5:
            for _ in range(rng.randint(1, 4)):
                op.store.create(st.PODS, _mkpod(rng, i))
                i += 1
        elif action < 0.65:
            pods = [p for p in op.store.list(st.PODS) if not p.meta.deleting]
            if pods:
                victim = rng.choice(pods)
                victim.meta.finalizers = []
                op.store.update(st.PODS, victim)
                op.store.delete(st.PODS, victim.meta.name)
        elif action < 0.8:
            insts = op.cloud.describe_instances()
            if insts:
                op.interruption_queue.send(Message(kind=SPOT_INTERRUPTION,
                                      instance_id=rng.choice(insts).id))
        else:
            insts = op.cloud.describe_instances()
            if insts:  # kill the instance out from under its node
                op.cloud.terminate_instances([rng.choice(insts).id])
        op.manager.tick()
        if step % 7 == 0:
            clock.advance(rng.choice([1, 5, 31]))
        _check_invariants(op, step)

    # convergence: give GC/liveness/termination room, then settle
    clock.advance(120)
    op.manager.settle()
    clock.advance(120)
    op.manager.settle()
    _check_invariants(op, "end")
    _assert_converged(op)


@pytest.mark.parametrize("seed", range(2))
def test_chaos_with_crash_restore(seed, tmp_path):
    """Kill the control plane mid-churn and restore from the periodic
    snapshot: the rebuilt cluster must pass the same invariants and
    converge — durability under fire, not just in the directed
    snapshot tests."""
    rng = random.Random(2000 + seed)
    snap = str(tmp_path / "snap.bin")
    clock = FakeClock()
    op = new_kwok_operator(clock=clock, snapshot_path=snap,
                           snapshot_interval_s=2.0)
    op.store.create(st.NODEPOOLS, mkpool())
    i = 0

    def churn(op, steps):
        nonlocal i
        for step in range(steps):
            action = rng.random()
            if action < 0.55:
                for _ in range(rng.randint(1, 3)):
                    op.store.create(st.PODS, _mkpod(rng, i))
                    i += 1
            elif action < 0.75:
                insts = op.cloud.describe_instances()
                if insts:
                    op.interruption_queue.send(Message(
                        kind=SPOT_INTERRUPTION,
                        instance_id=rng.choice(insts).id))
            else:
                insts = op.cloud.describe_instances()
                if insts:
                    op.cloud.terminate_instances([rng.choice(insts).id])
            op.manager.tick()
            clock.advance(1)
            _check_invariants(op, step)

    churn(op, 25)
    # hard crash: a fresh operator restores from the snapshot file (shares
    # the FakeClock: the restore rebase handles epoch continuity)
    op2 = new_kwok_operator(clock=clock, snapshot_path=snap,
                            snapshot_interval_s=2.0)
    _check_invariants(op2, "post-restore")
    churn(op2, 25)
    clock.advance(120)
    op2.manager.settle()
    clock.advance(120)
    op2.manager.settle()
    _check_invariants(op2, "end")
    _assert_converged(op2)

