"""Deploy renderer (Helm chart/values analog, charts/karpenter).

The load-bearing property: the rendered Deployment's KARPENTER_* env and the
flag table in operator/options.py are the SAME surface — settings values
round-trip through options.parse() bit-for-bit, and unknown settings keys
fail at render time (the drift the reference prevents by regenerating
settings.md from code, website/.../reference/settings.md:11).
"""

import os
from unittest import mock

import pytest
import yaml

from karpenter_tpu.deploy.render import (
    DEFAULT_VALUES,
    merge_values,
    render,
    render_yaml,
    settings_env,
)
from karpenter_tpu.operator import options as opt


def _by_kind(manifests, kind):
    return [m for m in manifests if m["kind"] == kind]


def test_default_render_shapes():
    ms = render()
    assert [m["kind"] for m in ms] == [
        "ServiceAccount",
        "Service",
        "PodDisruptionBudget",
        "PersistentVolumeClaim",
        "Deployment",
    ]
    dep = _by_kind(ms, "Deployment")[0]
    # HA scaffolding: 2 replicas (leader + standby) behind maxUnavailable=1
    assert dep["spec"]["replicas"] == 2
    assert _by_kind(ms, "PodDisruptionBudget")[0]["spec"]["maxUnavailable"] == 1
    c = dep["spec"]["template"]["spec"]["containers"][0]
    assert c["command"] == ["python", "-m", "karpenter_tpu.operator"]
    # probes target the health server the operator binary actually runs
    assert c["livenessProbe"]["httpGet"]["path"] == "/healthz"
    assert c["readinessProbe"]["httpGet"]["path"] == "/readyz"
    assert c["livenessProbe"]["httpGet"]["port"] == opt.Options().health_probe_port


def test_settings_roundtrip_through_options_parse():
    """Rendered env, applied as the environment, reproduces the values."""
    overrides = {
        "settings": {
            "batchIdleDurationS": 2.5,
            "batchMaxDurationS": 20.0,
            "preferencePolicy": "Ignore",
            "leaderElect": False,
            "featureGates": "SpotToSpotConsolidation=true",
            "solverBackend": "reference",
            "warmStart": False,
        }
    }
    env = settings_env(merge_values(overrides)["settings"])
    env_map = {e["name"]: e["value"] for e in env}
    with mock.patch.dict(os.environ, env_map, clear=False):
        o = opt.parse([])
    assert o.batch_idle_duration_s == 2.5
    assert o.batch_max_duration_s == 20.0
    assert o.preference_policy == "Ignore"
    assert o.leader_elect is False
    assert o.gates() == {"SpotToSpotConsolidation": True}
    assert o.solver_backend == "reference"
    assert o.warm_start is False


def test_every_option_field_is_reachable_from_values():
    """Any Options field may appear in values.settings (full flag surface)."""
    from dataclasses import fields

    from karpenter_tpu.deploy.render import _camel

    all_settings = {_camel(f.name): getattr(opt.Options(), f.name) for f in fields(opt.Options)}
    env = settings_env(all_settings)
    assert len(env) == len(all_settings)
    names = {e["name"] for e in env}
    assert "KARPENTER_BATCH_IDLE_DURATION_S" in names
    assert "KARPENTER_SNAPSHOT_PATH" in names


def test_unknown_settings_key_rejected():
    with pytest.raises(ValueError, match="does not match any option"):
        settings_env({"noSuchFlag": 1})


def test_yaml_output_parses_and_merge_is_deep():
    out = render_yaml({"controller": {"resources": {"requests": {"cpu": "2"}}}})
    docs = list(yaml.safe_load_all(out))
    assert len(docs) == 5
    dep = [d for d in docs if d["kind"] == "Deployment"][0]
    res = dep["spec"]["template"]["spec"]["containers"][0]["resources"]
    assert res["requests"]["cpu"] == "2"
    # deep-merge preserved the sibling default, and DEFAULT_VALUES unmutated
    assert res["requests"]["memory"] == "1Gi"
    assert DEFAULT_VALUES["controller"]["resources"]["requests"]["cpu"] == "1"


def test_crds_export_reflects_enforced_rules():
    """--crds emits the admission rules GENERATED from the enforcing code
    (the CRD-chart analog) — spot-check values against the validators."""
    from karpenter_tpu.api import validation as v
    from karpenter_tpu.api import wellknown as wk

    docs = v.rules_document()
    assert [d["metadata"]["name"] for d in docs] == [
        "nodepools.karpenter.sh", "nodeclaims.karpenter.sh",
    ]
    spec = docs[0]["spec"]
    assert set(spec["restrictedLabelDomains"]) == set(v._RESTRICTED_DOMAINS)
    assert set(spec["carvedOutDomains"]) == set(v._CARVED_OUT_DOMAINS)
    assert wk.ZONE_LABEL in spec["wellKnownAllowedKeys"]
    assert spec["budgets"]["nodes"] == v._BUDGET_NODES_RE.pattern
    # nodeclaims share the requirement path: allowlists must be present too
    assert docs[1]["spec"]["wellKnownAllowedKeys"] == spec["wellKnownAllowedKeys"]
    # real round-trip through the CLI's multi-doc YAML output
    blob = "---\n".join(yaml.safe_dump(d, sort_keys=False) for d in docs)
    parsed = list(yaml.safe_load_all(blob))
    assert parsed == docs


def test_default_manifests_match_golden():
    """Golden-file discipline (the reference's userdata goldens,
    pkg/providers/launchtemplate/testdata/*.golden): the default-rendered
    manifests are a reviewed artifact — any change must be deliberate.
    Regenerate with:
      python -c "from karpenter_tpu.deploy.render import render_yaml; \
open('tests/testdata/deploy_default.golden.yaml','w').write(render_yaml())"
    """
    here = os.path.dirname(__file__)
    golden = open(os.path.join(here, "testdata", "deploy_default.golden.yaml")).read()
    assert render_yaml() == golden


def test_crds_export_matches_golden():
    """Pins the SHIPPED artifact: the golden compares against the same
    crds_yaml() the CLI prints. Regenerate with:
      python -c "from karpenter_tpu.deploy.render import crds_yaml; \
open('tests/testdata/crds.golden.yaml','w').write(crds_yaml())"
    """
    from karpenter_tpu.deploy.render import crds_yaml

    here = os.path.dirname(__file__)
    golden = open(os.path.join(here, "testdata", "crds.golden.yaml")).read()
    assert crds_yaml() == golden


def test_render_rejects_lease_without_state_volume():
    """stateVolume off + leasePath set = container-local leases on both
    replicas = split brain; the render must refuse (r5 review finding)."""
    with pytest.raises(ValueError, match="stateVolume"):
        render({"stateVolume": None})


def test_render_rejects_unnamed_state_storage_class():
    """The RWX requirement must be explicit: empty storageClassName would
    silently bind the commonly-RWO default SC and strand both replicas."""
    with pytest.raises(ValueError, match="storageClassName"):
        render({"stateVolume": {"storageClassName": "", "size": "1Gi"}})
