"""Subprocess driver for the cross-process HA handoff test.

Runs a full operator as ONE OS process contending the flock'd file lease
(controllers/filelease.py) and snapshotting to the shared state dir —
the two-replica deployment shape deploy/render.py emits. Role "a" injects
the workload; role "b" is a pure standby. Each loop iteration writes a
status JSON the orchestrating test polls.

Usage: python -m tests.ha_driver <role> <shared-dir>
"""

import json
import os
import sys
import tempfile
import time


def main() -> None:
    role, dirpath = sys.argv[1], sys.argv[2]
    import jax

    jax.config.update("jax_platforms", "cpu")  # never touch the axon tunnel

    import karpenter_tpu.controllers.store as st
    from karpenter_tpu.api.nodeclass import KwokNodeClass
    from karpenter_tpu.api.objects import NodePool, ObjectMeta, Pod
    from karpenter_tpu.operator.operator import new_kwok_operator
    from karpenter_tpu.utils.resources import Resources

    op = new_kwok_operator(
        leader_elect=True,
        identity=f"proc-{role}",
        lease_path=os.path.join(dirpath, "leader.lease"),
        lease_s=1.5,
        renew_s=0.5,
        snapshot_path=os.path.join(dirpath, "state.snap"),
        snapshot_interval_s=0.2,
    )
    if role == "a":
        op.store.create(st.NODEPOOLS, NodePool(meta=ObjectMeta(name="default")))
        op.store.create(st.NODECLASSES, KwokNodeClass(meta=ObjectMeta(name="default")))
        for i in range(5):
            op.store.create(
                st.PODS,
                Pod(meta=ObjectMeta(name=f"w{i}", uid=f"w{i}"),
                    requests=Resources.parse({"cpu": "1", "memory": "2Gi"})),
            )

    status_path = os.path.join(dirpath, f"status-{role}.json")
    while True:
        op.manager.tick()
        status = {
            "pid": os.getpid(),
            "leader": op.manager.elector.is_leader(),
            "bound": sum(1 for p in op.store.list(st.PODS) if p.node_name),
            "claims": sorted(c.name for c in op.store.list(st.NODECLAIMS)),
            "instances": sorted(i.id for i in op.cloud.describe_instances()),
        }
        fd, tmp = tempfile.mkstemp(dir=dirpath, prefix=f".st-{role}-")
        with os.fdopen(fd, "w") as f:
            json.dump(status, f)
        os.replace(tmp, status_path)
        time.sleep(0.05)


if __name__ == "__main__":
    main()
