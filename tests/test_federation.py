"""Federated solver fleets (ISSUE 18; SPEC.md "Federation semantics").

Pins the four federation contracts:
- consistent-hash routing is STABLE under membership change: removing a
  host re-homes only its own tenants, adding one steals ~1/N — surviving
  hosts never shuffle tenants among themselves;
- cross-host failover drops NOTHING and preserves per-tenant FIFO: a
  fenced host's outstanding solves requeue onto survivors in submission
  order, and a zombie host's late results are dead (first-wins facades);
- journal replication is an event-time wire: the replica tail rebuilds a
  peer store decision-identical to the lost host's, immune to later
  mutation of the live objects;
- knobs off = no router exists and the single-process path is untouched
  (the fail-closed boot validations refuse every half-configured deploy).
"""

import dataclasses as dc
import io

import numpy as np
import pytest

from karpenter_tpu.api.objects import ObjectMeta, Pod
from karpenter_tpu.controllers import store as st
from karpenter_tpu.parallel import hostmesh as hm
from karpenter_tpu.provisioning.scheduler import SolverInput
from karpenter_tpu.solver.backend import ReferenceSolver
from karpenter_tpu.solver.federation import (
    FederationConfigError,
    FederationMisroute,
    FederationRouter,
    HashRing,
    JournalReplicator,
    parse_hosts,
)
from karpenter_tpu.solver.pipeline import (
    DISRUPTION,
    PROVISIONING,
    SolveService,
    SolveTicket,
)
from karpenter_tpu.state.cluster import ClusterJournal
from karpenter_tpu.utils.resources import Resources

from tests.test_solver_parity import ZONES, pool


def mkpod(name, cpu="500m", mem="512Mi"):
    return Pod(meta=ObjectMeta(name=name, uid=name),
               requests=Resources.parse({"cpu": cpu, "memory": mem}))


def small_input(num_pods=6):
    sizes = [("250m", "512Mi"), ("500m", "1Gi"), ("1", "2Gi")]
    pods = [mkpod(f"p{i:03d}", *sizes[i % len(sizes)])
            for i in range(num_pods)]
    return SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)


# ---------------------------------------------------------------- hash ring


class TestHashRing:
    def test_remove_moves_only_the_removed_hosts_tenants(self):
        ring = HashRing(["h0", "h1", "h2", "h3"])
        homes = {f"t{i}": ring.route(f"t{i}") for i in range(400)}
        ring.remove("h2")
        for t, old in homes.items():
            new = ring.route(t)
            if old == "h2":
                assert new != "h2"
            else:
                # survivors never shuffle tenants among themselves
                assert new == old, f"{t} moved {old} -> {new} on h2 removal"

    def test_add_steals_a_bounded_fraction(self):
        ring = HashRing(["h0", "h1", "h2", "h3"])
        homes = {f"t{i}": ring.route(f"t{i}") for i in range(400)}
        ring.add("h4")
        moved = sum(1 for t, old in homes.items() if ring.route(t) != old)
        # ~1/5 expected; 2x slack bounds vnode variance without flaking
        assert moved <= 2 * 400 // 5, f"{moved}/400 moved on host add"
        for t, old in homes.items():
            new = ring.route(t)
            assert new in (old, "h4"), f"{t} moved {old} -> {new}, not to h4"

    def test_route_is_deterministic_and_order_insensitive(self):
        a = HashRing(["h0", "h1", "h2"])
        b = HashRing(["h2", "h1", "h0"])
        for i in range(50):
            assert a.route(f"t{i}") == b.route(f"t{i}")
        with pytest.raises(FederationConfigError):
            HashRing([]).route("t0")

    def test_parse_hosts_fail_closed(self):
        assert parse_hosts("a, b,c") == ["a", "b", "c"]
        with pytest.raises(FederationConfigError):
            parse_hosts("")
        with pytest.raises(FederationConfigError):
            parse_hosts("a,a")


# ----------------------------------------------------- router construction


class _FakeHost:
    """Deterministic inner service: records arrivals in order, resolves a
    ticket only when the test says so."""

    def __init__(self, name):
        self.name = name
        self.received = []  # (tenant_id, payload) in arrival order
        self.tickets = []

    def submit(self, inp, kind=PROVISIONING, rev=None, tenant_id=None):
        t = SolveTicket(kind, rev=rev, tenant_id=tenant_id)
        self.received.append((tenant_id, inp))
        self.tickets.append(t)
        return t

    def submit_fn(self, dispatch_fn, kind=DISRUPTION, tenant_id=None):
        return self.submit(dispatch_fn, kind=kind, tenant_id=tenant_id)

    def queue_depth(self):
        return sum(1 for t in self.tickets if not t.done())

    def occupancy(self):
        return 0.0

    def close(self):
        pass


def _tenants_on(router, host, n, universe=500):
    out = [f"t{i}" for i in range(universe)
           if router._ring.route(f"t{i}") == host]
    assert len(out) >= n, f"universe too small for {n} tenants on {host}"
    return out[:n]


class TestRouterConfig:
    def test_self_must_be_member(self):
        with pytest.raises(FederationConfigError):
            FederationRouter(["h0", "h1"], self_host="h9")

    def test_attach_unknown_host_rejected(self):
        r = FederationRouter(["h0"], self_host="h0")
        with pytest.raises(FederationConfigError):
            r.attach("h9", _FakeHost("h9"))

    def test_unattached_route_is_typed_misroute(self):
        r = FederationRouter(["h0", "h1"], self_host="h0")
        r.attach("h0", _FakeHost("h0"))
        # a tenant homed on the UNATTACHED peer must fail closed, not be
        # served locally (that would fork the peer's journal cursor)
        tn = next(f"t{i}" for i in range(200) if r.route(f"t{i}") == "h1")
        t = r.submit("job", kind=DISRUPTION, tenant_id=tn)
        assert isinstance(t.error(), FederationMisroute)
        assert r.federation_stats()["misroutes"] == 1

    def test_untenanted_traffic_stays_local(self):
        r = FederationRouter(["h0", "h1", "h2"], self_host="h1")
        assert r.route(None) == "h1"


# ------------------------------------------------------- failover contract


class TestCrossHostFailover:
    def _rig(self):
        hosts = ["h0", "h1", "h2"]
        router = FederationRouter(hosts, self_host="h0")
        fakes = {h: _FakeHost(h) for h in hosts}
        for h, f in fakes.items():
            router.attach(h, f)
        return router, fakes

    def test_zero_drops_and_per_tenant_fifo(self):
        router, fakes = self._rig()
        ta, tb = _tenants_on(router, "h1", 2)
        # interleaved per-tenant streams, all homed on h1
        facades = [
            router.submit(f"{tn}#{k}", kind=DISRUPTION, tenant_id=tn)
            for k in range(3) for tn in (ta, tb)
        ]
        assert fakes["h1"].received == [
            (tn, f"{tn}#{k}") for k in range(3) for tn in (ta, tb)
        ]
        requeued = router.fail_host("h1", reason="test")
        assert requeued == 6
        assert router.healthy_hosts() == ["h0", "h2"]
        # every tenant re-homed onto ONE survivor, streams in FIFO order
        for tn in (ta, tb):
            new_home = router.route(tn)
            assert new_home in ("h0", "h2")
            got = [tag for (t, tag) in fakes[new_home].received if t == tn]
            assert got == [f"{tn}#{k}" for k in range(3)], got
        # resolve the survivors' tickets: every facade resolves, 0 dropped
        for h in ("h0", "h2"):
            for t in fakes[h].tickets:
                t._deliver(result=f"ok-by-{h}")
        for f in facades:
            assert f.result(timeout=5).startswith("ok-by-")
        assert router.federation_stats()["dropped"] == 0

    def test_zombie_host_results_are_dead(self):
        router, fakes = self._rig()
        (tn,) = _tenants_on(router, "h1", 1)
        facade = router.submit("job", kind=DISRUPTION, tenant_id=tn)
        router.fail_host("h1", reason="test")
        new_home = router.route(tn)
        fakes[new_home].tickets[-1]._deliver(result="survivor")
        # the fenced host answers LATE: first-wins must keep the survivor's
        for t in fakes["h1"].tickets:
            t._deliver(result="zombie")
        assert facade.result(timeout=5) == "survivor"

    def test_fenced_host_errors_are_swallowed(self):
        router, fakes = self._rig()
        (tn,) = _tenants_on(router, "h1", 1)
        facade = router.submit("job", kind=DISRUPTION, tenant_id=tn)
        router.fail_host("h1", reason="test")
        for t in fakes["h1"].tickets:
            t._deliver(error=RuntimeError("host torn down"))
        assert not facade.done()  # the requeued copy owns the facade now
        new_home = router.route(tn)
        fakes[new_home].tickets[-1]._deliver(result="ok")
        assert facade.result(timeout=5) == "ok"

    def test_in_flight_host_loss_requeues_not_drops(self):
        """The host dies UNDER an in-flight solve (WorkerDead surfaces on
        the inner ticket before anyone called fail_host): the router must
        fence the host itself and requeue — the facade never sees the
        pipe error."""
        router, fakes = self._rig()
        (tn,) = _tenants_on(router, "h1", 1)
        facade = router.submit("job", kind=DISRUPTION, tenant_id=tn)
        fakes["h1"].tickets[0]._deliver(
            error=hm.WorkerDead("h1: EOF mid-call"))
        assert "h1" not in router.healthy_hosts()
        assert not facade.done()
        new_home = router.route(tn)
        fakes[new_home].tickets[-1]._deliver(result="ok")
        assert facade.result(timeout=5) == "ok"
        assert router.federation_stats()["cross_host_failovers"] == 1

    def test_last_healthy_host_is_never_fenced(self):
        router, fakes = self._rig()
        router.fail_host("h0")
        router.fail_host("h1")
        assert router.healthy_hosts() == ["h2"]
        router.fail_host("h2")  # refused: never strand the ring
        assert router.healthy_hosts() == ["h2"]
        # and an in-flight loss on the last host SURFACES the error
        (tn,) = _tenants_on(router, "h2", 1, universe=2000)
        facade = router.submit("job", kind=DISRUPTION, tenant_id=tn)
        fakes["h2"].tickets[-1]._deliver(error=hm.WorkerDead("h2: gone"))
        assert isinstance(facade.error(), hm.WorkerDead)

    def test_tenant_moves_counted_on_rehome(self):
        router, fakes = self._rig()
        (tn,) = _tenants_on(router, "h1", 1)
        router.route(tn)  # establish placement: first sight is not a move
        before = router.federation_stats()["tenant_moves"]
        router.fail_host("h1", reason="test")
        router.route(tn)
        assert router.federation_stats()["tenant_moves"] == before + 1

    def test_restore_host_rejoins_the_ring(self):
        router, fakes = self._rig()
        router.fail_host("h1", reason="test")
        assert "h1" not in router.healthy_hosts()
        router.restore_host("h1")
        assert router.healthy_hosts() == ["h0", "h1", "h2"]

    def test_failover_composes_with_live_services(self):
        # real SolveServices as hosts: fence one, resubmit, decisions land
        hosts = ["h0", "h1"]
        router = FederationRouter(hosts, self_host="h0", own_services=True)
        for h in hosts:
            router.attach(h, SolveService(ReferenceSolver()))
        try:
            inp = small_input()
            (tn,) = _tenants_on(router, "h1", 1)
            r1 = router.submit(
                inp, kind=DISRUPTION, tenant_id=tn).result(timeout=30)
            router.fail_host("h1", reason="test")
            r2 = router.submit(
                inp, kind=DISRUPTION, tenant_id=tn).result(timeout=30)
            # the surviving peer reaches the lost host's exact decisions
            assert r1.placements == r2.placements
            assert router.federation_stats()["dropped"] == 0
        finally:
            router.close()


# -------------------------------------------------------- journal replication


class TestJournalReplication:
    def _rig(self, maxlen=4096):
        store = st.Store()
        journal = ClusterJournal(store)
        rep = JournalReplicator(journal, peers=["peer"], maxlen=maxlen)
        return store, journal, rep

    def test_tail_is_event_time_snapshot(self):
        store, journal, rep = self._rig()
        p = mkpod("a")
        store.create(st.PODS, p)
        p.requests = Resources.parse({"cpu": "8", "memory": "8Gi"})
        tail = rep.tail("peer")
        assert len(tail) == 1
        # the replica holds the EVENT-TIME object, not the live reference
        # (the journal's own events are level-triggered live refs)
        assert tail[0].obj is not p
        assert tail[0].obj.requests.get_("cpu") != p.requests.get_("cpu")

    def test_lag_tracks_acks(self):
        store, journal, rep = self._rig()
        for i in range(3):
            store.create(st.PODS, mkpod(f"p{i}"))
        assert rep.lag("peer") == 3 and rep.lag() == 3
        assert len(rep.drain_peer("peer")) == 3
        assert rep.lag("peer") == 0
        store.create(st.PODS, mkpod("late"))
        assert rep.lag("peer") == 1

    def test_catch_up_parity_is_decision_identical(self):
        """The failover contract end to end: a peer re-baselined from the
        replicated tail must make the SAME decisions the lost host would
        have — same pods in, same placements out."""
        store, journal, rep = self._rig()
        inp = small_input(8)
        for p in inp.pods:
            store.create(st.PODS, p)
        store.delete(st.PODS, inp.pods[-1].meta.name)
        mut = store.get(st.PODS, inp.pods[0].meta.name)
        mut.requests = Resources.parse({"cpu": "2", "memory": "4Gi"})
        store.update(st.PODS, mut)

        rebuilt = rep.rebuild_store("peer")
        orig = sorted(store.list(st.PODS), key=lambda p: p.meta.name)
        peer = sorted(rebuilt.list(st.PODS), key=lambda p: p.meta.name)
        assert [p.meta.name for p in orig] == [p.meta.name for p in peer]

        solver = ReferenceSolver()
        res_orig = solver.solve(dc.replace(inp, pods=orig))
        res_peer = solver.solve(dc.replace(inp, pods=peer))
        assert res_orig.placements == res_peer.placements
        assert res_orig.errors == res_peer.errors

    def test_replication_needs_peers(self):
        store = st.Store()
        journal = ClusterJournal(store)
        with pytest.raises(FederationConfigError):
            JournalReplicator(journal, peers=[])

    def test_bounded_tail_overflows_oldest_first(self):
        store, journal, rep = self._rig(maxlen=2)
        for i in range(5):
            store.create(st.PODS, mkpod(f"p{i}"))
        tail = rep.tail("peer")
        assert [e.key for e in tail] == ["default/p3", "default/p4"]
        assert rep.stats["overflows"] == 3


# ----------------------------------------------- single-process parity path


class TestSingleProcessParity:
    def test_router_is_decision_identical_to_direct(self):
        inp = small_input()
        direct = SolveService(ReferenceSolver())
        try:
            want = direct.submit(inp, kind=DISRUPTION).result(timeout=30)
        finally:
            direct.close()
        router = FederationRouter(["solo"], self_host="solo",
                                  own_services=True)
        router.attach("solo", SolveService(ReferenceSolver()))
        try:
            got = router.submit(inp, kind=DISRUPTION).result(timeout=30)
        finally:
            router.close()
        assert want.placements == got.placements
        assert want.errors == got.errors

    def test_knobs_off_constructs_no_router(self):
        from karpenter_tpu.operator.operator import new_kwok_operator

        op = new_kwok_operator()
        assert op.federation is None and op.replicator is None
        # the submit seam is the plain pipeline service, not a facade
        assert type(op.solve_service).__name__ == "SolveService"

    def test_knobs_on_wires_router_and_replicator(self):
        from karpenter_tpu.operator.operator import new_kwok_operator

        op = new_kwok_operator(
            federation_hosts="h0,h1", federation_self="h0",
            journal_replicate=True,
        )
        assert type(op.federation).__name__ == "FederationRouter"
        assert op.solve_service is op.federation
        assert op.federation.route(None) == "h0"
        assert op.replicator is not None and op.replicator.peers == ["h1"]

    def test_boot_validations_fail_closed(self):
        from karpenter_tpu.operator import options as opt

        for argv in (
            ["--federation-hosts", "h0,h1"],  # no self
            ["--federation-hosts", "h0,h1", "--federation-self", "h9"],
            ["--federation-self", "h0"],  # self without hosts
            ["--journal-replicate", "true"],  # replication without hosts
            ["--federation-hosts", "h0,h0", "--federation-self", "h0"],
        ):
            with pytest.raises(SystemExit):
                opt.parse(argv)
        o = opt.parse(["--federation-hosts", "h0,h1",
                       "--federation-self", "h1",
                       "--journal-replicate", "true"])
        assert o.federation_self == "h1" and o.journal_replicate


# ------------------------------------------------------- host mesh plumbing


class TestHostMesh:
    def test_worker_protocol_in_process(self):
        """worker_main against in-memory pipes: ping, job-level error reply
        (the worker must answer, not die), clean exit."""
        inb = io.BytesIO()
        for job in ({"kind": "ping"}, {"kind": "nope"}, {"kind": "exit"}):
            hm._write_frame(inb, job)
        inb.seek(0)
        outb = io.BytesIO()
        assert hm.worker_main(stdin=inb, stdout=outb) == 0
        outb.seek(0)
        ping = hm._read_frame(outb)
        err = hm._read_frame(outb)
        assert ping["ok"] and ping["result"]["pid"] > 0
        assert not err["ok"] and "ValueError" in err["error"]

    def test_tree_concat_reassembles_named_tuples(self):
        import collections

        T = collections.namedtuple("T", "a b")
        parts = [
            T(np.arange(2).reshape(2, 1), np.ones((2, 3))),
            T(np.arange(2, 4).reshape(2, 1), np.zeros((2, 3))),
        ]
        out = hm._tree_concat(parts)
        assert out.a.shape == (4, 1) and out.b.shape == (4, 3)
        np.testing.assert_array_equal(out.a[:, 0], [0, 1, 2, 3])

    def test_worker_death_is_typed(self):
        w = hm.WorkerProc("t-dead")
        try:
            assert w.call({"kind": "ping"})["pid"] > 0
            w.kill()
            with pytest.raises(hm.WorkerDead):
                w.call({"kind": "ping"})
        finally:
            w.close()

    def test_pool_rejects_undividable_blocks(self):
        pool_ = hm.HostMeshPool.__new__(hm.HostMeshPool)  # no subprocesses
        pool_.workers = [object(), object(), object()]
        with pytest.raises(ValueError, match="do not divide"):
            pool_.scatter_blocks(np.zeros((4, 2, 3)), np.zeros((4, 2)),
                                 rest=(), max_claims=8)
