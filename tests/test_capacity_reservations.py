"""Capacity reservations: reserved capacity type end-to-end.

Mirrors the reference's ODCR behavior (SURVEY.md §2.2 capacityreservation,
§2.2 offering: reserved offerings priced odPrice/10M so they always win price
ordering; launch/terminate bookkeeping; reserved->on-demand flip on expiry,
§2.4 nodeclaim/capacityreservation).
"""

import pytest

from karpenter_tpu.api import wellknown as wk
from karpenter_tpu.controllers import store as st
from karpenter_tpu.operator.operator import new_kwok_operator
from karpenter_tpu.providers.capacityreservation import CapacityReservation

from tests.test_e2e_kwok import FakeClock, mkpod, mkpool


@pytest.fixture
def op():
    clock = FakeClock()
    o = new_kwok_operator(clock=clock)
    o.clock = clock
    return o


def add_reservation(op, instance_type="m5.large", zone="zone-1a", count=2, expires=None):
    op.cloud_provider.reservations.add(
        CapacityReservation(
            id=f"cr-{instance_type}-{zone}",
            instance_type=instance_type,
            zone=zone,
            total=count,
            available=count,
            expires_at=expires,
        )
    )
    return f"cr-{instance_type}-{zone}"


class TestReservations:
    def test_reserved_offering_preferred(self, op):
        add_reservation(op, "m5.large", "zone-1a", count=2)
        op.store.create(st.NODEPOOLS, mkpool())
        op.store.create(st.PODS, mkpod("p", cpu="500m", mem="1Gi"))
        op.manager.settle()
        claim = op.store.list(st.NODECLAIMS)[0]
        assert claim.capacity_type == wk.CAPACITY_TYPE_RESERVED
        assert claim.instance_type == "m5.large"
        assert claim.zone == "zone-1a"
        # bookkeeping decremented
        res = op.cloud_provider.reservations.get("cr-m5.large-zone-1a")
        assert res.available == 1

    def test_exhausted_reservation_falls_back(self, op):
        add_reservation(op, "m5.large", "zone-1a", count=1)
        op.store.create(st.NODEPOOLS, mkpool())
        for i in range(2):
            op.store.create(
                st.PODS,
                mkpod(f"p{i}", cpu="1500m", mem="6Gi",
                      node_selector={wk.ZONE_LABEL: "zone-1a" if i == 0 else "zone-1b"}),
            )
        op.manager.settle()
        claims = sorted(op.store.list(st.NODECLAIMS), key=lambda c: c.zone)
        assert claims[0].capacity_type == wk.CAPACITY_TYPE_RESERVED  # zone-1a used it
        assert claims[1].capacity_type != wk.CAPACITY_TYPE_RESERVED  # zone-1b: none there

    def test_terminate_returns_capacity(self, op):
        rid = add_reservation(op, "m5.large", "zone-1a", count=1)
        op.store.create(st.NODEPOOLS, mkpool())
        op.store.create(st.PODS, mkpod("p", cpu="500m", mem="1Gi"))
        op.manager.settle()
        assert op.cloud_provider.reservations.get(rid).available == 0
        claim = op.store.list(st.NODECLAIMS)[0]
        pod = op.store.get(st.PODS, "p")
        pod.meta.finalizers = []
        op.store.delete(st.PODS, "p")
        op.store.delete(st.NODECLAIMS, claim.name)
        op.manager.settle()
        assert op.cloud_provider.reservations.get(rid).available == 1

    def test_expiry_flips_to_on_demand(self, op):
        add_reservation(op, "m5.large", "zone-1a", count=1, expires=2000.0)
        # WhenEmpty: keep consolidation from immediately replacing the
        # flipped (now expensive) node with spot — the flip itself is under test
        op.store.create(st.NODEPOOLS, mkpool(consolidation="WhenEmpty"))
        op.store.create(st.PODS, mkpod("p", cpu="500m", mem="1Gi"))
        op.manager.settle()
        claim = op.store.list(st.NODECLAIMS)[0]
        assert claim.capacity_type == wk.CAPACITY_TYPE_RESERVED
        assert claim.price < 0.001  # nearly-free reserved pricing
        op.clock.advance(1500)  # past expires_at=2000 (clock starts at 1000)
        op.manager.settle()
        claim = op.store.list(st.NODECLAIMS)[0]
        assert claim.capacity_type == wk.CAPACITY_TYPE_ON_DEMAND
        assert claim.price > 0.01  # od price now
        node = op.store.get(st.NODES, claim.node_name)
        assert node.meta.labels[wk.CAPACITY_TYPE_LABEL] == wk.CAPACITY_TYPE_ON_DEMAND
