"""Bench regression sentinel: tools/bench_gate.py contract (ISSUE 14).

The gate is the first CI-able perf guardrail over the BENCH_rNN.json
records, so its exact semantics are pinned here:
- a fabricated regression (2x a latency, half a throughput) exits 1,
  an in-tolerance drift exits 0;
- marker records (`value: -1`, `backend_unavailable`) and keys missing
  on a side are SKIPPED — a host without the accelerator toolchain
  gates clean (exit 0) with a loud vacuous-gate warning, never red;
- direction is per key (per_sec/rate/hit/... higher-is-better), and
  per-key tolerances fall through the p99/first-call heuristic;
- usage/IO errors exit 2, distinguishable from a real regression.

The CLI is spec-loaded from tools/ (not a package): the same mechanism
bench.py --baseline uses.
"""

import importlib.util
import json
import pathlib

import pytest

ROOT = pathlib.Path(__file__).resolve().parents[1]

_spec = importlib.util.spec_from_file_location(
    "bench_gate", ROOT / "tools" / "bench_gate.py")
gate = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(gate)


BASELINE = {
    "n": 7,
    "rc": 0,
    "cmd": "python bench.py",
    "parsed": {
        "metric": "solve_p99_50k_pods_x_700_types",
        "value": 120.0,
        "e2e_p50_ms": 180.0,
        "e2e_p99_ms": 260.0,
        "kernel_pipelined_ms": 11.0,
        "arrival_batches_per_sec": 50.0,
        "upload_bytes_per_solve": 4096.0,
        "first_call_s": 30.0,
        "backend_unavailable": False,
    },
}


def _write(tmp_path, name, obj):
    p = tmp_path / name
    p.write_text(json.dumps(obj))
    return str(p)


def _current(**overrides):
    cur = json.loads(json.dumps(BASELINE))
    cur["n"] = BASELINE["n"] + 1
    cur["parsed"].update(overrides)
    return cur


def test_in_tolerance_drift_exits_zero(tmp_path, capsys):
    base = _write(tmp_path, "base.json", BASELINE)
    cur = _write(tmp_path, "cur.json", _current(
        e2e_p50_ms=198.0,                 # +10% inside the 20% tolerance
        arrival_batches_per_sec=45.0,     # -10% inside 20% (higher-better)
        upload_bytes_per_solve=4096.0,
    ))
    assert gate.main(["--baseline", base, "--current", cur]) == 0
    out = capsys.readouterr().out
    assert "REGRESSED" not in out and "vacuous" not in out


def test_doubled_latency_exits_one(tmp_path, capsys):
    base = _write(tmp_path, "base.json", BASELINE)
    cur = _write(tmp_path, "cur.json", _current(e2e_p50_ms=360.0))
    assert gate.main(["--baseline", base, "--current", cur]) == 1
    assert "e2e_p50_ms" in capsys.readouterr().out


def test_halved_throughput_exits_one_direction_aware(tmp_path, capsys):
    """arrival_batches_per_sec is higher-is-better: HALVING it regresses
    even though the raw value went down, and DOUBLING it must not."""
    base = _write(tmp_path, "base.json", BASELINE)
    worse = _write(tmp_path, "worse.json",
                   _current(arrival_batches_per_sec=25.0))
    better = _write(tmp_path, "better.json",
                    _current(arrival_batches_per_sec=100.0))
    assert gate.main(["--baseline", base, "--current", worse]) == 1
    assert gate.main(["--baseline", base, "--current", better]) == 0


def test_marker_record_gates_clean_but_loud(tmp_path, capsys):
    """A backend_unavailable marker (value -1, the BENCH_r05.json shape)
    has nothing comparable: exit 0 with the vacuous-gate warning."""
    base = _write(tmp_path, "base.json", BASELINE)
    marker = _write(tmp_path, "marker.json", {
        "n": 9, "rc": 0,
        "parsed": {"metric": "solve_p99_50k_pods_x_700_types", "value": -1,
                   "backend_unavailable": True,
                   "reason": "jax/tpu runtime not importable"},
    })
    assert gate.main(["--baseline", base, "--current", marker]) == 0
    assert "vacuous" in capsys.readouterr().out


def test_missing_keys_are_skipped_not_failed(tmp_path, capsys):
    base = _write(tmp_path, "base.json", BASELINE)
    cur = _write(tmp_path, "cur.json", {
        "n": 8, "parsed": {"e2e_p50_ms": 181.0}})
    assert gate.main(["--baseline", base, "--current", cur]) == 0
    assert "skipped" in capsys.readouterr().out


def test_io_and_usage_errors_exit_two(tmp_path):
    base = _write(tmp_path, "base.json", BASELINE)
    assert gate.main(["--baseline", str(tmp_path / "nope.json"),
                      "--current", base]) == 2
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    assert gate.main(["--baseline", base, "--current", str(bad)]) == 2
    assert gate.main(["--baseline", base, "--current", base,
                      "--default-tolerance", "-1"]) == 2


def test_current_defaults_to_newest_bench_record(tmp_path):
    _write(tmp_path, "BENCH_r02.json", BASELINE)
    _write(tmp_path, "BENCH_r10.json", _current(e2e_p50_ms=1000.0))
    assert gate.newest_bench_record(str(tmp_path)).endswith("BENCH_r10.json")
    base = _write(tmp_path, "BENCH_r02.json", BASELINE)
    assert gate.main(["--baseline", base]) == 1  # picked r10, which regressed


def test_tolerance_heuristics():
    assert gate.tolerance_for("e2e_p99_ms", 0.20) == 0.30          # per-key
    assert gate.tolerance_for("other_p99_ms", 0.20) == 0.30        # p99 rule
    assert gate.tolerance_for("first_call_s", 0.20) == 1.00        # cold start
    assert gate.tolerance_for("some_counter", 0.15) == 0.15        # default
    assert gate.higher_is_better("arrival_batches_per_sec")
    assert gate.higher_is_better("arena_hit_rate")
    assert not gate.higher_is_better("e2e_p50_ms")


def test_extract_metrics_flattens_and_skips_bookkeeping():
    got = gate.extract_metrics(BASELINE)
    assert got["solve_p99_50k_pods_x_700_types"] == 120.0  # metric/value pair
    assert got["e2e_p50_ms"] == 180.0                      # parsed flattens
    assert "n" not in got and "rc" not in got              # bookkeeping
    assert "backend_unavailable" not in got                # bools skipped
    nested = gate.extract_metrics({"parsed": {"sub": {"x_ms": 5.0}}})
    assert nested == {"sub.x_ms": 5.0}


@pytest.mark.parametrize("record", ["BENCH_r03.json", "BENCH_r05.json"])
def test_repo_records_self_gate_clean(record):
    """Every shipped record must gate clean against itself — the identity
    diff is the smoke test CI runs without a perf box."""
    path = ROOT / record
    if not path.exists():
        pytest.skip(f"{record} not in the tree")
    assert gate.main(["--baseline", str(path), "--current", str(path)]) == 0
