"""Requirements set-algebra (karpenter_tpu/scheduling/requirements.py).

Covers the operator semantics the reference exercises through
karpenter core pkg/scheduling (SURVEY.md §2.1): In/NotIn/Exists/DoesNotExist/
Gt/Lt, intersection, Compatible, minValues propagation.
"""

import pytest

from karpenter_tpu.scheduling.requirements import (
    DOES_NOT_EXIST,
    EXISTS,
    GT,
    IN,
    LT,
    NOT_IN,
    Requirement,
    Requirements,
)


class TestRequirement:
    def test_in(self):
        r = Requirement.create("zone", IN, ["a", "b"])
        assert r.has("a") and r.has("b") and not r.has("c")
        assert r.len_hint() == 2

    def test_not_in(self):
        r = Requirement.create("zone", NOT_IN, ["a"])
        assert not r.has("a") and r.has("b")
        assert r.len_hint() is None

    def test_exists(self):
        r = Requirement.create("zone", EXISTS)
        assert r.has("anything")

    def test_does_not_exist(self):
        r = Requirement.create("zone", DOES_NOT_EXIST)
        assert not r.has("a")
        assert r.allows_absent()

    def test_gt_lt(self):
        gt = Requirement.create("gen", GT, ["4"])
        assert gt.has("5") and not gt.has("4") and not gt.has("x")
        lt = Requirement.create("gen", LT, ["4"])
        assert lt.has("3") and not lt.has("4")

    def test_gt_requires_single_value(self):
        with pytest.raises(ValueError):
            Requirement.create("gen", GT, ["1", "2"])

    def test_intersect_in_in(self):
        a = Requirement.create("z", IN, ["a", "b"])
        b = Requirement.create("z", IN, ["b", "c"])
        assert a.intersect(b).values_list() == ["b"]

    def test_intersect_in_notin(self):
        a = Requirement.create("z", IN, ["a", "b"])
        b = Requirement.create("z", NOT_IN, ["a"])
        assert a.intersect(b).values_list() == ["b"]

    def test_intersect_notin_notin(self):
        a = Requirement.create("z", NOT_IN, ["a"])
        b = Requirement.create("z", NOT_IN, ["b"])
        c = a.intersect(b)
        assert not c.has("a") and not c.has("b") and c.has("x")

    def test_intersect_gt_in(self):
        a = Requirement.create("gen", GT, ["4"])
        b = Requirement.create("gen", IN, ["3", "5", "7"])
        assert a.intersect(b).values_list() == ["5", "7"]

    def test_intersects_disjoint(self):
        a = Requirement.create("z", IN, ["a"])
        b = Requirement.create("z", IN, ["b"])
        assert not a.intersects(b)
        assert a.intersects(Requirement.create("z", EXISTS))


class TestRequirements:
    def test_add_intersects_same_key(self):
        rs = Requirements.of(Requirement.create("z", IN, ["a", "b"]))
        rs.add(Requirement.create("z", IN, ["b", "c"]))
        assert rs["z"].values_list() == ["b"]

    def test_from_labels(self):
        rs = Requirements.from_labels({"arch": "amd64"})
        assert rs["arch"].values_list() == ["amd64"]

    def test_compatible(self):
        pod = Requirements.of(Requirement.create("zone", IN, ["a", "b"]))
        node = Requirements.of(Requirement.create("zone", IN, ["b"]))
        assert pod.compatible(node)
        assert node.compatible(pod)
        other = Requirements.of(Requirement.create("zone", IN, ["c"]))
        assert not pod.compatible(other)

    def test_compatible_missing_key_is_unconstrained(self):
        pod = Requirements.of(Requirement.create("special", IN, ["x"]))
        node = Requirements()
        assert pod.compatible(node)

    def test_strictly_compatible_requires_key_present(self):
        pod = Requirements.of(Requirement.create("special", IN, ["x"]))
        node_labels = Requirements.from_labels({"zone": "a"})
        assert not pod.strictly_compatible(node_labels)
        assert pod.strictly_compatible(Requirements.from_labels({"special": "x", "zone": "a"}))

    def test_strictly_compatible_does_not_exist(self):
        pod = Requirements.of(Requirement.create("special", DOES_NOT_EXIST))
        assert pod.strictly_compatible(Requirements.from_labels({"zone": "a"}))
        assert not pod.strictly_compatible(Requirements.from_labels({"special": "x"}))

    def test_labels_single_valued(self):
        rs = Requirements.of(
            Requirement.create("a", IN, ["1"]),
            Requirement.create("b", IN, ["1", "2"]),
        )
        assert rs.labels() == {"a": "1"}

    def test_min_values(self):
        rs = Requirements.from_node_selector_terms(
            [{"key": "family", "operator": IN, "values": ["m5", "c5"], "minValues": 2}]
        )
        assert rs.has_min_values()
        assert rs["family"].min_values == 2

    def test_min_values_max_on_intersect(self):
        a = Requirement.create("f", IN, ["x", "y", "z"], min_values=1)
        b = Requirement.create("f", IN, ["x", "y"], min_values=2)
        assert a.intersect(b).min_values == 2
