"""Static drift checks for the kernel argument contract.

ffd.ARG_SPEC is the single source of truth for the kernel's positional
tensor arguments; backend.host_kernel_args builds in that order, the arena
keys residency per-entry on it, and the AOT prewarm sizes shapes from
_AOT_SHAPES. Any of those drifting out of sync fails at runtime with shape
errors at best and silent misbinding at worst — so the alignment is
asserted statically here, with no device work.
"""

import inspect

from karpenter_tpu.solver import backend
from karpenter_tpu.solver.tpu import ffd

STATICS = ("max_claims", "emit_takes", "zone_engine")


def test_arg_spec_matches_kernel_signature():
    params = list(inspect.signature(ffd.ffd_solve.__wrapped__).parameters)
    tensor = [p for p in params if p not in STATICS]
    assert tuple(tensor) == ffd.ARG_SPEC, (
        "ffd_solve's positional tensor params drifted from ffd.ARG_SPEC"
    )
    # statics trail the tensor args, so positional call sites stay valid
    assert params == tensor + [p for p in params if p in STATICS]


def test_arg_index_matches_spec():
    assert ffd.ARG_INDEX == {n: i for i, n in enumerate(ffd.ARG_SPEC)}


def test_aot_shape_table_covers_spec():
    assert set(backend._AOT_SHAPES) == set(ffd.ARG_SPEC), (
        "_AOT_SHAPES keys drifted from ffd.ARG_SPEC"
    )


def test_staleness_partition_covers_spec():
    static, per_solve = backend.STATIC_CORE_NAMES, backend.PER_SOLVE_NAMES
    assert not (static & per_solve), static & per_solve
    assert static | per_solve == set(ffd.ARG_SPEC), (
        "arena staleness partition drifted from ffd.ARG_SPEC"
    )


def test_host_kernel_args_arity_and_provenance():
    from karpenter_tpu.solver.encode import encode, quantize_input

    from tests.test_solver_parity import ZONES, mkpod, pool

    from karpenter_tpu.provisioning.scheduler import SolverInput

    inp = SolverInput(pods=[mkpod("p0"), mkpod("p1")], nodes=[],
                      nodepools=[pool()], zones=ZONES)
    enc = encode(quantize_input(inp))
    solver = backend.TPUSolver()
    host_args, dims, prov = backend.host_kernel_args(enc, solver._bucket)
    assert len(host_args) == len(ffd.ARG_SPEC)
    assert len(prov) == len(ffd.ARG_SPEC)
    # D (domain-axis width) is derived the same way prewarm_aot derives it
    dims = dict(dims)
    dims["D"] = int(host_args[ffd.ARG_SPEC.index("zone_col_mask")].shape[0])
    for name, a, tok in zip(ffd.ARG_SPEC, host_args, prov):
        assert tuple(a.shape) == tuple(
            dims[s] for s in backend._AOT_SHAPES[name]
        ), f"{name}: host shape diverges from _AOT_SHAPES"
        if name in backend.STATIC_CORE_NAMES:
            assert tok is not None and tok[1] == name, (
                f"{name}: static-core entry missing provenance token"
            )
        else:
            assert tok is None, (
                f"{name}: per-solve entry must take the digest path"
            )
    # the device-facing wrapper preserves arity
    dev_args, dev_dims = backend.kernel_args(enc, solver._bucket)
    assert len(dev_args) == len(ffd.ARG_SPEC)
    assert {k: dims[k] for k in dev_dims} == dict(dev_dims)


# -- checkpointed-scan resume (ISSUE 5) --------------------------------------

RESUME_STATICS = STATICS + ("ckpt_every", "n_ckpt")


def test_checkpoint_ring_layout_matches_ffd_state():
    """The ring's per-slot snapshots ARE FFDState pytrees (tree_map-stacked),
    and the resume entry point replays from one of them — a field added to
    FFDState without flowing through CheckpointRing would resume from a
    truncated carry and silently diverge. Pin the structural contract."""
    assert ffd.CheckpointRing._fields == ("states", "prefix")
    # the stacked-states leaf set is exactly FFDState's (annotation is the
    # contract; construction uses tree_map over an FFDState so it cannot
    # partially drift)
    assert "FFDState" in str(ffd.CheckpointRing.__annotations__["states"])
    # the scan carry the kernel snapshots — every decision-bearing register
    assert ffd.FFDState._fields == (
        "e_cum", "c_cum", "c_mask", "c_zc_bits", "c_gbits", "c_pool",
        "used", "p_usage", "e_cm", "e_co", "c_cm", "c_co",
        "v_count", "v_owner_z", "c_vm", "c_vo",
    ), "FFDState fields changed: update checkpoint ring + resume plumbing"


def test_resume_entry_points_share_the_tensor_contract():
    """ffd_solve_ckpt and ffd_resume take the SAME 36 positional tensors as
    ffd_solve (resume with a leading init_state), so the arena's per-entry
    residency and the suffix dispatch's args[2:] splice stay valid."""
    import inspect

    for fn, lead in (("ffd_solve_ckpt", ()), ("ffd_resume", ("init_state",))):
        params = list(inspect.signature(getattr(ffd, fn).__wrapped__).parameters)
        tensor = [p for p in params if p not in RESUME_STATICS]
        assert tuple(tensor) == lead + ffd.ARG_SPEC, (
            f"{fn}'s tensor params drifted from ffd.ARG_SPEC"
        )
        assert params == tensor + list(RESUME_STATICS), (
            f"{fn}: statics must trail as ({', '.join(RESUME_STATICS)})"
        )


def test_cold_entry_point_signature_is_frozen():
    """ffd_solve keeps its pre-resume signature: no ckpt statics. vmap call
    sites (parallel/sharded.py, consolidate.py) and the AOT prewarm bind it
    positionally; checkpoint harvesting belongs ONLY to ffd_solve_ckpt."""
    import inspect

    params = list(inspect.signature(ffd.ffd_solve.__wrapped__).parameters)
    assert "ckpt_every" not in params and "n_ckpt" not in params


# -- on-device decode + relax ladder (ISSUE 6) --------------------------------


def test_ladder_entry_point_shares_the_tensor_contract():
    """ffd_solve_ladder takes run_ladder then the SAME 36 positional tensors
    as ffd_solve, statics trailing — so _ladder_arg can splice the arena's
    resident args after the rung table without re-deriving the order."""
    params = list(inspect.signature(ffd.ffd_solve_ladder.__wrapped__).parameters)
    tensor = [p for p in params if p not in STATICS]
    assert tuple(tensor) == ("run_ladder",) + ffd.ARG_SPEC, (
        "ffd_solve_ladder's tensor params drifted from run_ladder + ARG_SPEC"
    )
    assert params == tensor + list(STATICS), (
        f"ffd_solve_ladder: statics must trail as ({', '.join(STATICS)})"
    )


# -- mesh-sharded solve (ISSUE 7) ---------------------------------------------


def test_sharded_entry_point_shares_the_tensor_contract():
    """ffd_solve_sharded takes the SAME 36 positional tensors as ffd_solve
    (run_group/run_count carry a leading [Nd] block axis but keep their
    names and positions), statics trailing — so the sharded dispatch can
    splice the arena's resident args[2:] and the AOT prewarm can bind
    positionally, exactly like every other entry point."""
    params = list(
        inspect.signature(ffd.ffd_solve_sharded.__wrapped__).parameters
    )
    tensor = [p for p in params if p not in STATICS]
    assert tuple(tensor) == ffd.ARG_SPEC, (
        "ffd_solve_sharded's tensor params drifted from ffd.ARG_SPEC"
    )
    assert params == tensor + list(STATICS), (
        f"ffd_solve_sharded: statics must trail as ({', '.join(STATICS)})"
    )


def test_shard_block_alignment_is_pinned():
    """The run-axis bucket multiple IS the shard-block alignment contract:
    backend buckets Sp with mult=floor=16, so every power-of-2 mesh up to
    16 devices divides the padded run axis into equal contiguous blocks
    with no resharding padding (encode.mesh_run_blocks relies on it, and
    backend._shard_mesh caps the mesh width at it)."""
    assert ffd.SHARD_BLOCK_MULT == 16
    for n in (1, 2, 4, 8, 16):
        assert ffd.SHARD_BLOCK_MULT % n == 0


def test_mesh_run_blocks_wire_layout():
    """Per-shard wire layout: blocks are CONTIGUOUS row-major slices of the
    scan order — block d of the [Nd, Sblk] upload is runs
    [d*Sblk, (d+1)*Sblk) exactly, so the stitch's left-to-right carry
    exchange walks the same order the one-device scan does. Non-dividing
    shard counts must refuse, not truncate."""
    import numpy as np
    import pytest

    from karpenter_tpu.solver.encode import UnpackableInput, mesh_run_blocks

    rg = np.arange(32, dtype=np.int32)
    rc = (np.arange(32, dtype=np.int32) % 5) + 1
    for nd in (1, 2, 4, 8, 16):
        bg, bc = mesh_run_blocks(rg, rc, nd)
        assert bg.shape == (nd, 32 // nd) and bc.shape == (nd, 32 // nd)
        assert (bg.reshape(-1) == rg).all() and (bc.reshape(-1) == rc).all()
        assert bg.flags["C_CONTIGUOUS"] and bc.flags["C_CONTIGUOUS"]
    with pytest.raises(UnpackableInput):
        mesh_run_blocks(rg, rc, 3)
    with pytest.raises(UnpackableInput):
        mesh_run_blocks(rg, rc, 0)


def test_claim_delta_wire_layout_is_pinned():
    """backend._pack_dispatch's unpack slices the flat delta buffer by these
    constants; ffd's compaction writes it. Either side drifting silently
    misdecodes, so the layout is pinned here, not discovered at runtime."""
    assert ffd.DELTA_HEADER_WORDS == 3, (
        "delta header is [overflow, entry_count, uniq_meta_count]"
    )
    assert ffd.DELTA_ENTRY_U16 == 2, (
        "each entry word packs (code, count) as two uint16 halves"
    )


def test_delta_capacity_properties():
    """Capacity functions gate compile-variant count (quantum-bucketed) and
    the overflow carve-out (hard ceilings). Monotone in every argument so a
    growing fleet never shrinks the buffer mid-session."""
    caps = [backend.delta_capacity(n, 32, 224, 512) for n in (1, 10_000, 50_000)]
    assert caps == sorted(caps)
    for c in caps:
        assert c % backend.DELTA_CAP_QUANTUM == 0 and c >= backend.DELTA_CAP_QUANTUM
    # total_pods is a hard ceiling: 1 pod never needs >1 quantum of entries
    assert backend.delta_capacity(1, 1024, 4096, 4096) == backend.DELTA_CAP_QUANTUM
    # structural ceiling Sp*(E+M) binds tiny problems regardless of pod count
    assert backend.delta_capacity(10**9, 2, 3, 4) == backend.DELTA_CAP_QUANTUM

    us = [backend.delta_uniq_capacity(s, 512) for s in (1, 32, 256)]
    assert us == sorted(us)
    for u in us:
        assert u % backend.DELTA_UNIQ_QUANTUM == 0 and u >= backend.DELTA_UNIQ_QUANTUM
    # Mb is a hard ceiling: can't have more distinct meta rows than claims
    assert backend.delta_uniq_capacity(10_000, 8) == backend.DELTA_UNIQ_QUANTUM


# -- scheduling classes (ISSUE 9) ---------------------------------------------


def test_arg_spec_stays_frozen_at_36():
    """The class tensors ride the CLASS_ARG_SPEC side table, NOT ffd.ARG_SPEC
    — the 36-tensor contract (arena residency, AOT shapes, resume/ladder/
    sharded splices) must not widen for priority/gang support."""
    assert len(ffd.ARG_SPEC) == 36
    assert not set(ffd.CLASS_ARG_SPEC) & set(ffd.ARG_SPEC)


def test_class_side_table_matches_encode_fields():
    """CLASS_ARG_SPEC names are 1:1 with EncodedInput's class fields, and the
    gang tables pair off [NG]-shaped: run_prio16/run_gang are per-run [S],
    gang_size/gang_min_ranks per-gang."""
    import dataclasses

    from karpenter_tpu.solver.encode import EncodedInput

    assert ffd.CLASS_ARG_SPEC == (
        "run_prio16", "run_gang", "gang_size", "gang_min_ranks"
    )
    enc_fields = {f.name for f in dataclasses.fields(EncodedInput)}
    assert set(ffd.CLASS_ARG_SPEC) <= enc_fields


def test_gang_kernel_signatures():
    """The planner kernels take the class tensors in CLASS_ARG_SPEC order —
    run-level tensors first, gang tables trailing — so every caller
    (scheduling_class planner legs, native host mirror) can splice the
    encode side table positionally."""
    params = list(inspect.signature(ffd.gang_commit.__wrapped__).parameters)
    assert params == ["run_placed", "run_gang", "gang_size", "gang_min_ranks"]
    params = list(
        inspect.signature(ffd.preemption_plan.__wrapped__).parameters
    )
    assert params == [
        "node_free", "victim_prio", "victim_req", "victim_ok",
        "node_ok", "need", "pod_prio",
    ]


def test_eviction_table_wire_layout_is_pinned():
    """pack_evictions/unpack_evictions share this layout with the claim-delta
    discipline: uint16 words, header [overflow, entry_count], 2 words per
    entry (node_idx, victim_idx); overflow = counted decline."""
    assert ffd.EVICT_HEADER_WORDS == 2, (
        "eviction header is [overflow, entry_count]"
    )
    assert ffd.EVICT_ENTRY_U16 == 2, (
        "each eviction entry is (node_idx, victim_idx) as two uint16 words"
    )
    buf = ffd.pack_evictions([(3, 1), (0, 7)])
    assert buf.dtype.name == "uint16"
    overflow, rows = ffd.unpack_evictions(buf)
    assert not overflow and rows == [(3, 1), (0, 7)]
    overflow, rows = ffd.unpack_evictions(ffd.pack_evictions([(2**16, 0)]))
    assert overflow and rows == []


def test_gang_stage_carry_layout():
    """GangStage is the staged-commit carry: the base FFDState plus the gang
    id being staged and its running member count. A field added to FFDState
    flows through `base` automatically; adding one HERE without updating the
    merge/rollback seam would silently truncate the rollback."""
    assert ffd.GangStage._fields == ("base", "gang", "members_placed")


# -- explain wire (ISSUE 12) ---------------------------------------------------


def test_explain_arg_spec_is_pinned():
    """EXPLAIN_ARG_SPEC is a SIDE table (CLASS_ARG_SPEC precedent): it must
    not leak into the frozen 36-tensor ffd.ARG_SPEC, and its names are the
    wire contract the backend dispatch and the AOT story build against."""
    assert ffd.EXPLAIN_ARG_SPEC == (
        "take_e", "run_group", "group_req", "node_free", "node_compat",
        "node_zone", "node_ct", "group_zone", "group_ct", "group_topo",
        "group_aff", "e_count", "g_count",
    )
    assert not set(ffd.EXPLAIN_ARG_SPEC) & {"max_claims", "emit_takes"}
    assert len(ffd.ARG_SPEC) == 36  # explain must not widen the scan


def test_explain_pack_signature_matches_spec():
    params = list(inspect.signature(ffd.explain_pack.__wrapped__).parameters)
    assert tuple(p for p in params if p != "top_k") == ffd.EXPLAIN_ARG_SPEC, (
        "explain_pack's positional params drifted from EXPLAIN_ARG_SPEC"
    )
    assert params[-1] == "top_k"  # the single static


def test_explain_wire_layout_is_pinned():
    """Header [overflow, g_count, top_k] + per group one count word and
    top_k 1-word entries (e | reason << 16, -1 empty) — the claim-delta
    discipline: fixed header, uint16 payload halves, overflow carve-out."""
    assert ffd.EXPLAIN_HEADER_WORDS == 3
    assert ffd.EXPLAIN_ENTRY_WORDS == 1
    assert ffd.explain_words(4, 8) == 3 + 4 * (1 + 8)


# -- streaming delta-solve (ISSUE 13) -----------------------------------------


def test_event_batch_wire_layout_is_pinned():
    """The run-table edit triplet is the streaming h2d wire: int32 rows of
    (pos, gid, cnt), padded to the compile bucket with EVENT_PAD_POS rows
    that the drop-mode scatter discards. encode_cache.run_table_events
    writes it, arena.apply_run_events pads+ships it, ffd_apply_events
    scatters it — all three against these constants."""
    assert ffd.EVENT_ENTRY_WORDS == 3, "event rows are (pos, gid, cnt)"
    assert ffd.EVENT_PAD_POS == -1, "pad rows drop via scatter mode='drop'"
    params = list(
        inspect.signature(ffd.ffd_apply_events.__wrapped__).parameters
    )
    assert params == ["run_group", "run_count", "events"], (
        "ffd_apply_events' tensor params drifted"
    )


def test_run_table_events_wire_roundtrip():
    """Host-side contract of the diff: applying the triplets to the previous
    tables reproduces the new ones exactly; shape mismatch and over-budget
    diffs refuse (None) instead of truncating."""
    import numpy as np

    from karpenter_tpu.solver.encode_cache import run_table_events

    prev_rg = np.arange(16, dtype=np.int32)
    prev_rc = np.ones(16, dtype=np.int32)
    rg, rc = prev_rg.copy(), prev_rc.copy()
    rg[3] = 99
    rc[7] = 5
    ev = run_table_events(prev_rg, prev_rc, rg, rc)
    assert ev.dtype == np.int32 and ev.shape[1] == ffd.EVENT_ENTRY_WORDS
    got_rg, got_rc = prev_rg.copy(), prev_rc.copy()
    got_rg[ev[:, 0]] = ev[:, 1]
    got_rc[ev[:, 0]] = ev[:, 2]
    assert (got_rg == rg).all() and (got_rc == rc).all()
    assert run_table_events(prev_rg, prev_rc, rg, rc, max_events=1) is None
    assert run_table_events(prev_rg[:8], prev_rc[:8], rg, rc) is None
    empty = run_table_events(rg, rc, rg, rc)
    assert empty.shape == (0, 3)


def test_streaming_entry_point_signatures():
    """The provisioner binds pump()/pending_pods()/build_input(pending); the
    backend stage calls arena.apply_run_events(host_args, prov, sharding,
    ns); the model drains with journal.drain(after_seq). Pin all of them —
    the streaming seam is positional at every layer."""
    from karpenter_tpu.solver.arena import ArgumentArena
    from karpenter_tpu.solver.streaming import StreamingSolver
    from karpenter_tpu.state.cluster import ClusterJournal

    assert list(inspect.signature(StreamingSolver.pump).parameters) == ["self"]
    assert list(
        inspect.signature(StreamingSolver.pending_pods).parameters
    ) == ["self"]
    assert list(
        inspect.signature(StreamingSolver.build_input).parameters
    ) == ["self", "pending"]
    assert list(
        inspect.signature(ClusterJournal.drain).parameters
    ) == ["self", "after_seq"]
    assert list(
        inspect.signature(ArgumentArena.apply_run_events).parameters
    ) == ["self", "host_args", "prov", "sharding", "ns"]


# -- convex ADMM backend (ISSUE 19) --------------------------------------------


def test_convex_kernel_signature_matches_spec():
    """admm_pack is a SIDE entry point (CLASS_ARG_SPEC precedent): its
    tensor params are pinned by convex.CONVEX_ARG_SPEC — the arena keys
    residency and prewarm_aot sizes shapes on that order — with the single
    static trailing, and it must not widen the frozen 36-tensor FFD
    contract."""
    from karpenter_tpu.solver import convex

    params = list(inspect.signature(convex.admm_pack.__wrapped__).parameters)
    tensor = [p for p in params if p not in convex.CONVEX_STATICS]
    assert tuple(tensor) == convex.CONVEX_ARG_SPEC, (
        "admm_pack's positional tensor params drifted from CONVEX_ARG_SPEC"
    )
    assert params == tensor + list(convex.CONVEX_STATICS), (
        "admm_pack: statics must trail the tensor args"
    )
    assert len(ffd.ARG_SPEC) == 36  # the convex backend rides a side table


def test_explain_reasons_match_decoder_names():
    """The kernel-side enum and the decoder-side names (obs/explain) are one
    contract — a code without a name renders as 'codeN' in records, a name
    without a code can never appear on the wire."""
    from karpenter_tpu.obs import explain as obsexplain

    assert dict((c, n) for n, c in ffd.EXPLAIN_REASONS) == obsexplain.REASON_NAMES
    codes = [c for _, c in ffd.EXPLAIN_REASONS]
    assert codes == sorted(codes) == list(range(len(codes))), (
        "reason codes must stay dense and ordered — precedence is the wire"
    )


# -- sparse constraint engine (ISSUE 20) --------------------------------------


def test_sparse_arg_spec_is_pinned():
    """SPARSE_ARG_SPEC is the wire layout of the compacted V/Q side tables:
    run_q_idx then run_v_idx, each a -1-padded [S, K] i32 CSR-style index
    table. The arena's "sparse" residency class and every sparse entry
    point bind these two positionally ahead of ARG_SPEC — pin the order."""
    assert ffd.SPARSE_ARG_SPEC == ("run_q_idx", "run_v_idx")


SPARSE_LEADS = {
    "ffd_solve_sparse": ((), STATICS),
    "ffd_solve_ckpt_sparse": ((), RESUME_STATICS),
    "ffd_resume_sparse": (("init_state",), RESUME_STATICS),
    "ffd_solve_ladder_sparse": (("run_ladder",), STATICS),
    "ffd_solve_sharded_sparse": ((), STATICS),
}


def test_sparse_entry_points_share_the_tensor_contract():
    """Every sparse entry point takes its dense twin's lead (init_state /
    run_ladder), then SPARSE_ARG_SPEC, then the SAME 36 ARG_SPEC tensors,
    statics trailing — so backend's _sparse_arg can prepend the resident
    sparse pair to the arena's args splice without re-deriving order, and
    the sharded path's [Nd, Sblk, K] blocks keep their names/positions."""
    for fn, (lead, statics) in SPARSE_LEADS.items():
        params = list(
            inspect.signature(getattr(ffd, fn).__wrapped__).parameters
        )
        tensor = [p for p in params if p not in statics]
        assert tuple(tensor) == lead + ffd.SPARSE_ARG_SPEC + ffd.ARG_SPEC, (
            f"{fn}'s tensor params drifted from SPARSE_ARG_SPEC + ARG_SPEC"
        )
        assert params == tensor + list(statics), (
            f"{fn}: statics must trail as ({', '.join(statics)})"
        )


def test_sparse_width_bucketing_is_pinned():
    """Sparse index widths quantize (mult=floor=8) so repeat solves with a
    drifting active-pair count reuse one compiled shape; the density gate's
    constants are part of the dispatch contract (SPEC.md "Sparse constraint
    semantics") — a silent change re-gates production fleets."""
    from karpenter_tpu.solver import encode

    assert encode.SPARSE_IDX_MULT == 8
    assert encode.SPARSE_IDX_FLOOR == 8
    assert encode.SPARSE_MIN_SIGS == 8
    assert encode.SPARSE_DENSITY_MAX == 0.25
    assert encode._sparse_width(0) == 8
    assert encode._sparse_width(8) == 8
    assert encode._sparse_width(9) == 16
    assert encode._sparse_width(17) == 24
