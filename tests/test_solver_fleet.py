"""SolverFleet semantics: routing, canary fencing, requeue, recovery, chaos.

The fleet (solver/fleet.py) fronts N SolveService owners behind the single-
service surface; these tests pin its contract: healthy-path parity and
provisioning coalescing survive the extra layer, a wedged owner — a HUNG
dispatch, injected via the faults.py wedge-class sites, never a raised one —
is fenced within `fence_after_misses` canary intervals, every in-flight and
queued request re-routes to a healthy owner (or the oracle) without a drop
or a double-act, and a released wedge recovers the owner through the
breaker's half-open probe behind a fresh service. All clock-injected; the
only real-time waits are the canary deadlines themselves (sub-second).
"""

import threading
import time

import pytest

from karpenter_tpu import faults
from karpenter_tpu.metrics.registry import (
    FLEET_FAILOVER,
    FLEET_HEALTHY,
    FLEET_REQUEUED,
)
from karpenter_tpu.provisioning.scheduler import SolverInput
from karpenter_tpu.solver.backend import ReferenceSolver, TPUSolver
from karpenter_tpu.solver.fleet import SolverFleet
from karpenter_tpu.solver.pipeline import (
    DISRUPTION,
    PROVISIONING,
    ServiceStopped,
    Superseded,
)
from karpenter_tpu.solver.resilient import ResilientSolver

from tests.test_batched_consolidation import ZONES, mkpod, pool
from tests.test_e2e_kwok import FakeClock


def mkinput(pod_name="a", cpu="250m"):
    return SolverInput(
        pods=[mkpod(pod_name, cpu=cpu)], nodes=[], nodepools=[pool()], zones=ZONES
    )


class TaggedOracle(ReferenceSolver):
    """Oracle-speed solver that honours the wedge-class fault sites the way
    TPUSolver does (tagged device_hang/device_lost checks on the dispatch
    path), so fleet fencing is testable without device solves."""

    def __init__(self):
        super().__init__()
        self.fault_tag = None
        self.solve_count = 0

    def solve(self, inp):
        faults.check("solver.device_hang", tag=self.fault_tag)
        faults.check("solver.device_lost", tag=self.fault_tag)
        self.solve_count += 1
        return super().solve(inp)


def mkfleet(size=2, fence_after_misses=2, canary_deadline_s=0.25,
            recovery_probe_s=10.0, clock=None, factory=None):
    clock = clock or FakeClock()
    solvers = []

    def _factory(i):
        s = (factory or (lambda _i: TaggedOracle()))(i)
        solvers.append(s)
        return s

    fleet = SolverFleet(
        _factory, size=size, clock=clock,
        canary_input_fn=lambda: mkinput("fleet-canary", cpu="100m"),
        canary_deadline_s=canary_deadline_s,
        fence_after_misses=fence_after_misses,
        recovery_probe_s=recovery_probe_s,
        fence_drain_s=0.1,
    )
    return fleet, solvers, clock


# ---------------------------------------------------------------- healthy path


def test_fleet_parity_and_stats_surface():
    fleet, solvers, _ = mkfleet(size=2)
    try:
        direct = ReferenceSolver().solve(mkinput("par"))
        via = fleet.submit(mkinput("par"), kind=PROVISIONING).result(timeout=10)
        assert via.placements == direct.placements
        assert via.errors == direct.errors
        assert len(via.claims) == len(direct.claims)
        assert fleet.healthy_owners() == 2
        assert fleet.probe_once() == {"owner-0": "ok", "owner-1": "ok"}
        st = fleet.stats
        assert st["fleet_submitted"] == 1
        assert st["healthy_owners"] == 2
        assert st["open"] == 0
        assert fleet.queue_depth() == 0
        assert 0.0 <= fleet.occupancy() <= 1.0
        for fn in (fleet.resume_stats, fleet.shard_stats, fleet.decode_stats):
            assert isinstance(fn(), dict)
    finally:
        fleet.close()


def test_provisioning_coalesces_on_primary_owner():
    """state_rev/Superseded semantics survive the fleet layer: all
    provisioning rides the primary owner, so a newer snapshot still
    supersedes every queued stale one."""
    gate = threading.Event()
    started = threading.Event()

    class Gated(TaggedOracle):
        # async seam blocking in DISPATCH (the GatedAsyncSolver idiom): the
        # owner's dispatcher parks on the gate, so later submissions stay
        # queued (coalescible) instead of dispatching immediately
        def solve_async(self, inp):
            from karpenter_tpu.solver.backend import AsyncSolve

            if inp.pods[0].meta.name == "hold":
                started.set()
                assert gate.wait(10)
            return AsyncSolve(lambda: TaggedOracle.solve(self, inp))

    fleet, _, _ = mkfleet(size=2, factory=lambda i: Gated())
    try:
        t0 = fleet.submit(mkinput("hold"), kind=PROVISIONING, rev=("r", 0))
        assert started.wait(10)
        t1 = fleet.submit(mkinput("q1"), kind=PROVISIONING, rev=("r", 1))
        t2 = fleet.submit(mkinput("q2"), kind=PROVISIONING, rev=("r", 2))
        assert t1.done() and t1.superseded()
        with pytest.raises(Superseded) as ei:
            t1.result()
        # the superseding handle maps back to the FLEET ticket
        assert ei.value.by is t2
        gate.set()
        assert t0.result(timeout=10) is not None
        assert t2.result(timeout=10) is not None
    finally:
        gate.set()
        fleet.close()


def test_fleet_close_resolves_every_ticket():
    fleet, _, _ = mkfleet(size=2)
    t = fleet.submit(mkinput("x"), kind=PROVISIONING)
    t.result(timeout=10)
    fleet.close()
    with pytest.raises(ServiceStopped):
        fleet.submit(mkinput("y"))
    assert fleet.unresolved() == 0


# ---------------------------------------------------------------- fencing


def test_canary_misses_fence_within_threshold():
    fleet, solvers, _ = mkfleet(size=2, fence_after_misses=2)
    plan = faults.FaultPlan(seed=3)
    wedge = plan.wedge("solver.device_hang", tag="owner-0")
    failovers0 = FLEET_FAILOVER.value(owner="owner-0")
    try:
        with faults.active(plan):
            v1 = fleet.probe_once()
            assert v1["owner-0"] == "miss" and v1["owner-1"] == "ok"
            assert fleet.healthy_owners() == 2  # one miss is not a fence
            v2 = fleet.probe_once()
            assert v2["owner-0"] == "fenced"
            assert fleet.healthy_owners() == 1
        assert FLEET_FAILOVER.value(owner="owner-0") == failovers0 + 1
        assert FLEET_HEALTHY.value() == 1.0
        assert FLEET_HEALTHY.value(owner="owner-0") == 0.0
        assert FLEET_HEALTHY.value(owner="owner-1") == 1.0
        # subsequent work routes to the healthy owner; the wedged owner
        # never executed a single solve (its canaries are parked in the wedge)
        assert fleet.submit(mkinput("after")).result(timeout=10) is not None
        assert solvers[0].solve_count == 0
    finally:
        wedge.release()
        fleet.close()


def test_wedged_inflight_requeues_without_drop_or_double_act():
    """A solve hung INSIDE a wedged owner re-routes on fence and completes
    exactly once: the wedge later releases, the stale owner-side result is
    dropped by first-wins delivery, and the solver that actually served the
    request is the healthy one."""
    fleet, solvers, _ = mkfleet(size=2, fence_after_misses=1)
    plan = faults.FaultPlan(seed=3)
    wedge = plan.wedge("solver.device_hang", tag="owner-0")
    requeued0 = FLEET_REQUEUED.value(target="owner")
    try:
        with faults.active(plan):
            t = fleet.submit(mkinput("inflight"), kind=PROVISIONING)
            deadline = time.monotonic() + 5
            while wedge.wedged == 0 and time.monotonic() < deadline:
                time.sleep(0.01)
            assert wedge.wedged >= 1  # the dispatch is parked in the wedge
            assert fleet.probe_once()["owner-0"] == "fenced"
            res = t.result(timeout=10)
            assert res.claims and res.claims[0].pod_uids == ["inflight"]
        assert FLEET_REQUEUED.value(target="owner") >= requeued0 + 1
        # release the wedge: the abandoned dispatch finishes late and its
        # delivery is DROPPED (first-wins) — no double-act
        wedge.release()
        time.sleep(0.2)
        assert solvers[1].solve_count >= 1
        assert fleet.unresolved() == 0
        assert fleet.stats["requeued"] >= 1
    finally:
        wedge.release()
        fleet.close()


def test_all_owners_fenced_degrades_to_oracle():
    fleet, solvers, _ = mkfleet(size=2, fence_after_misses=1)
    plan = faults.FaultPlan(seed=3)
    wedge = plan.wedge("solver.device_hang")  # untagged: every owner wedges
    try:
        with faults.active(plan):
            v = fleet.probe_once()
            assert set(v.values()) == {"fenced"}
            assert fleet.healthy_owners() == 0
            # inputs degrade to the oracle — decisions still flow
            res = fleet.submit(mkinput("degraded")).result(timeout=10)
            assert res.claims and res.claims[0].pod_uids == ["degraded"]
            # device-bound closures cannot replay on the oracle
            with pytest.raises(ServiceStopped):
                fleet.submit_fn(lambda: (lambda: "x"), kind=DISRUPTION).result(timeout=10)
        assert fleet.stats["oracle_degraded"] >= 1
        assert fleet.unresolved() == 0
    finally:
        wedge.release()
        fleet.close()


def test_device_lost_canary_errors_also_fence():
    """A raising canary (DeviceLost — the runtime reported the device gone)
    counts as a miss: raised and hung failures share the fencing path."""
    fleet, _, _ = mkfleet(size=2, fence_after_misses=2)
    plan = faults.FaultPlan(seed=3).script(
        "solver.device_lost", faults.DeviceLost, faults.DeviceLost,
        tag="owner-1",
    )
    try:
        with faults.active(plan):
            assert fleet.probe_once()["owner-1"] == "miss"
            assert fleet.probe_once()["owner-1"] == "fenced"
            assert fleet.healthy_owners() == 1
    finally:
        fleet.close()


# ---------------------------------------------------------------- recovery


def test_half_open_recovery_unfences_behind_fresh_service():
    fleet, solvers, clock = mkfleet(size=2, fence_after_misses=1,
                                    recovery_probe_s=10.0)
    plan = faults.FaultPlan(seed=3)
    wedge = plan.wedge("solver.device_hang", tag="owner-0")
    try:
        with faults.active(plan):
            assert fleet.probe_once()["owner-0"] == "fenced"
            old_service = fleet.owners[0].service
            # breaker still open on the injected clock: no probe yet
            assert fleet.probe_once()["owner-0"] == "fenced"
            # still wedged at half-open time: probe fails, stays fenced
            clock.advance(11)
            assert fleet.probe_once()["owner-0"] == "fenced"
            # released + next half-open window: recovery
            wedge.release()
            clock.advance(11)
            assert fleet.probe_once()["owner-0"] == "recovered"
        assert fleet.healthy_owners() == 2
        assert fleet.owners[0].service is not old_service  # fresh pipeline
        # the recovered owner serves provisioning again (primary routing)
        res = fleet.submit(mkinput("back")).result(timeout=10)
        assert res.claims
        assert fleet.stats["recoveries"] == 1
    finally:
        wedge.release()
        fleet.close()


def test_fenced_owner_arena_invalidated_for_readoption():
    """Fencing a TPU-backed owner drops its arena residency, so a recovered
    owner re-adopts from scratch (one full packed upload) instead of
    trusting buffers a wedged solve may have left mid-write."""
    fleet, solvers, clock = mkfleet(
        size=2, fence_after_misses=1, canary_deadline_s=5.0,
        factory=lambda i: TPUSolver(),
    )
    plan = faults.FaultPlan(seed=3)
    wedge = plan.wedge("solver.device_hang", tag="owner-0")
    try:
        # warm owner-0's arena with a real device solve
        res = fleet.submit(mkinput("warm"), kind=PROVISIONING).result(timeout=120)
        assert res.claims
        arena = fleet.owners[0].solver.arena
        inv0 = arena.stats["invalidations"]
        full0 = arena.stats["full_uploads"]
        with faults.active(plan):
            assert fleet.probe_once()["owner-0"] == "fenced"
        assert arena.stats["invalidations"] == inv0 + 1
        wedge.release()
        clock.advance(11)
        # the recovery canary itself is the first post-invalidate device
        # solve: it must pay a FULL re-adoption upload (no stale residency)
        assert fleet.probe_once()["owner-0"] == "recovered"
        res = fleet.submit(mkinput("readopt"), kind=PROVISIONING).result(timeout=120)
        assert res.claims
        assert arena.stats["full_uploads"] >= full0 + 1
    finally:
        wedge.release()
        fleet.close()


def test_arena_corrupt_fault_replays_on_fallback():
    """solver.arena_corrupt fires before residency is trusted: the per-
    request resilience layer classifies it as a device error, invalidates
    the arena, and the replay repairs residency — no fleet involvement
    needed for a RAISED fault."""
    rs = ResilientSolver(TPUSolver(), fallbacks=[ReferenceSolver()])
    plan = faults.FaultPlan(seed=3).script(
        "solver.arena_corrupt", faults.ArenaCorrupt
    )
    with faults.active(plan):
        res = rs.solve(mkinput("corrupt"))
    assert res.claims and res.claims[0].pod_uids == ["corrupt"]
    assert plan.fired["solver.arena_corrupt"] == 1
    assert rs.resilient_stats["fallback"] == 1
    # replay after the plan: residency re-adopts and the device path works
    res2 = rs.solve(mkinput("after-corrupt"))
    assert res2.claims


# ---------------------------------------------------------------- chaos


@pytest.mark.chaos
def test_chaos_wedge_mid_trace_decisions_identical_to_healthy_run():
    """ISSUE 8 acceptance: solver.device_hang injected on owner 0 mid-trace.
    The fleet fences it within fence_after_misses canary intervals, every
    in-flight and subsequent solve completes on another owner (or the
    oracle), and the decision sequence is IDENTICAL to a healthy
    single-owner run of the same trace."""
    inputs = [mkinput(f"c{i}", cpu=f"{200 + 50 * i}m") for i in range(6)]

    # healthy single-owner baseline
    baseline = [ReferenceSolver().solve(inp) for inp in inputs]

    fleet, solvers, _ = mkfleet(size=2, fence_after_misses=2)
    plan = faults.FaultPlan(seed=11)
    results = {}
    wedge = None
    try:
        with faults.active(plan):
            # pre-wedge: two healthy solves (disruption class: round-robins
            # across owners, so both serve traffic before the wedge)
            for i in (0, 1):
                results[i] = fleet.submit(inputs[i], kind=DISRUPTION).result(timeout=10)
            # wedge lands mid-trace: c2 hangs inside owner-0's dispatcher
            wedge = plan.wedge("solver.device_hang", tag="owner-0")
            tickets = {i: fleet.submit(inputs[i], kind=DISRUPTION) for i in (2, 3)}
            deadline = time.monotonic() + 5
            while wedge.wedged == 0 and time.monotonic() < deadline:
                time.sleep(0.01)
            assert wedge.wedged >= 1
            # fence within fence_after_misses canary intervals
            fleet.probe_once()
            verdicts = fleet.probe_once()
            assert verdicts["owner-0"] == "fenced"
            assert fleet.healthy_owners() == 1
            # in-flight + queued complete on the surviving owner
            for i, t in tickets.items():
                results[i] = t.result(timeout=10)
            # post-fence trace continues
            for i in (4, 5):
                results[i] = fleet.submit(inputs[i], kind=DISRUPTION).result(timeout=10)
        # decisions identical to the healthy single-owner run
        for i, base in enumerate(baseline):
            got = results[i]
            assert got.placements == base.placements, f"trace step {i}"
            assert got.errors == base.errors, f"trace step {i}"
            assert [c.pod_uids for c in got.claims] == [
                c.pod_uids for c in base.claims
            ], f"trace step {i}"
        assert fleet.unresolved() == 0  # nothing dropped
        assert fleet.stats["failovers"] == 1
    finally:
        if wedge is not None:
            wedge.release()
        fleet.close()


# ---------------------------------------------------------------- soak smoke


@pytest.mark.slow
def test_soak_suite_smoke_short_trace():
    """Satellite: the bench's churn-soak harness on a short trace — steady
    solves, one injected wedge, zero dropped solves."""
    import bench

    out = bench._soak_run(duration_steps=12, wedge_at_step=4, fleet_size=2,
                          canary_deadline_s=0.25, arrivals_per_step=2)
    assert out["soak_dropped_solves"] == 0
    assert out["soak_total_solves"] >= 12
    assert out["soak_failovers"] >= 1
    assert out["solves_per_sec"] > 0
    assert out["failover_recovery_ms"] >= 0


# ---------------------------------------------------------------- vault (ISSUE 17)


def _warm_vault(tmp_path):
    """Warm the process encode cache with one core and snapshot it, the way
    a serving operator's VaultController would have."""
    from karpenter_tpu.solver import encode as em
    from karpenter_tpu.solver.encode import quantize_input
    from karpenter_tpu.solver.vault import SolverStateVault

    em.encode(quantize_input(mkinput("vault-warm")))
    vault = SolverStateVault(str(tmp_path))
    assert vault.snapshot_now() is not None
    return vault


def test_device_lost_fence_with_vault_restores_zero_drops(tmp_path):
    """solver.device_lost fences an owner while a vault is wired: the fence
    path re-seeds the encode caches from the newest snapshot
    (fleet_stats["vault_restores"]) and every solve before and after the
    fence completes on the surviving owner — zero drops."""
    vault = _warm_vault(tmp_path)
    fleet, solvers, _ = mkfleet(size=2, fence_after_misses=1)
    fleet.vault = vault
    plan = faults.FaultPlan(seed=5)
    try:
        with faults.active(plan):
            pre = [fleet.submit(mkinput(f"pre{i}"), kind=DISRUPTION)
                   for i in range(4)]
            for t in pre:
                assert t.result(timeout=10).claims
            # the maintenance event lands AFTER the pre-fence traffic: the
            # next canary draws it and fences owner-0
            plan.fail_n(
                "solver.device_lost", 1,
                faults.DeviceLost("maintenance (injected)"), tag="owner-0",
            )
            assert fleet.probe_once()["owner-0"] == "fenced"
            post = [fleet.submit(mkinput(f"post{i}"), kind=DISRUPTION)
                    for i in range(4)]
            for t in post:
                assert t.result(timeout=10).claims
        assert fleet.fleet_stats["vault_restores"] == 1
        assert vault.stats["restores"] == 1
        assert vault.stats["donors_installed"] >= 1
        assert fleet.unresolved() == 0  # zero dropped solves
        assert fleet.stats["oracle_degraded"] == 0
    finally:
        fleet.close()


def test_all_owners_lost_with_vault_revives_instead_of_oracle(tmp_path):
    """Fleet-wide device_lost (a maintenance event hitting every owner) with
    a vault in hand: the LAST fence finds zero healthy owners and revives a
    fenced owner through a direct canary + vault restore instead of
    degrading every subsequent solve to the cold oracle."""
    vault = _warm_vault(tmp_path)
    fleet, solvers, _ = mkfleet(size=2, fence_after_misses=1)
    fleet.vault = vault
    # exactly one device_lost per owner's fencing canary; the revive canary
    # that follows draws from an empty script and succeeds
    plan = faults.FaultPlan(seed=5).script(
        "solver.device_lost", faults.DeviceLost, faults.DeviceLost,
    )
    try:
        with faults.active(plan):
            fleet.probe_once()
            assert fleet.healthy_owners() == 1  # revived, not zero
            res = fleet.submit(mkinput("revived")).result(timeout=10)
            assert res.claims and res.claims[0].pod_uids == ["revived"]
        assert fleet.fleet_stats["vault_restores"] >= 1
        assert fleet.stats["oracle_degraded"] == 0
        assert fleet.unresolved() == 0
    finally:
        fleet.close()


def test_vault_write_fault_mid_soak_keeps_serving(tmp_path):
    """Chaos soak: vault.write fails mid-run — snapshots SKIP (counted,
    throttled WARN) while every solve keeps landing, and the next cadence
    retry succeeds once the fault clears."""
    from karpenter_tpu.solver import encode as em
    from karpenter_tpu.solver.encode import quantize_input
    from karpenter_tpu.solver.vault import SolverStateVault

    em.encode(quantize_input(mkinput("soak-warm")))
    vault = SolverStateVault(str(tmp_path))
    fleet, _, _ = mkfleet(size=2)
    fleet.vault = vault
    plan = faults.FaultPlan(seed=5).fail_n(
        "vault.write", 2, OSError("disk full (injected)")
    )
    try:
        with faults.active(plan):
            for step in range(6):
                t = fleet.submit(mkinput(f"soak{step}"), kind=DISRUPTION)
                assert t.result(timeout=10).claims  # serving never stops
                vault.snapshot_now()  # the controller cadence
        assert vault.stats["write_failures"] == 2
        assert vault.stats["snapshots"] == 4  # retries landed post-fault
        assert len(vault.candidates()) >= 1
        assert fleet.unresolved() == 0
    finally:
        fleet.close()
