"""Budget cron windows + full disruption-cost ranking
(website/.../concepts/disruption.md:274-330).

Clock-driven: a fake wall clock steps through budget windows; ranking tests
assert candidate order under pod-deletion-cost annotations and node lifetime
remaining.
"""

from datetime import datetime, timezone

import pytest

from karpenter_tpu.api import wellknown as wk
from karpenter_tpu.api.objects import (
    Budget,
    Disruption,
    NodeClaimTemplate,
    NodePool,
    ObjectMeta,
    Pod,
    TopologySpreadConstraint,
)
from karpenter_tpu.controllers import store as st
from karpenter_tpu.disruption.controller import DisruptionController
from karpenter_tpu.disruption.cron import Cron, in_window
from karpenter_tpu.operator.operator import new_kwok_operator
from karpenter_tpu.utils.resources import Resources

from tests.test_e2e_kwok import FakeClock


def ts(y, mo, d, h, mi) -> float:
    return datetime(y, mo, d, h, mi, tzinfo=timezone.utc).timestamp()


class TestCron:
    def test_basic_match(self):
        c = Cron("0 9 * * *")
        assert c.matches(datetime(2026, 7, 29, 9, 0, tzinfo=timezone.utc))
        assert not c.matches(datetime(2026, 7, 29, 9, 1, tzinfo=timezone.utc))
        assert not c.matches(datetime(2026, 7, 29, 10, 0, tzinfo=timezone.utc))

    def test_ranges_steps_lists(self):
        c = Cron("*/15 8-17 * * 1-5")
        dt = datetime(2026, 7, 29, 8, 45, tzinfo=timezone.utc)  # a Wednesday
        assert c.matches(dt)
        assert not c.matches(dt.replace(minute=50))
        sat = datetime(2026, 8, 1, 8, 45, tzinfo=timezone.utc)
        assert not c.matches(sat)

    def test_sunday_is_zero(self):
        c = Cron("0 0 * * 0")
        sun = datetime(2026, 8, 2, 0, 0, tzinfo=timezone.utc)
        assert c.matches(sun)
        assert not c.matches(sun.replace(day=3))  # Monday

    def test_invalid(self):
        with pytest.raises(ValueError):
            Cron("0 9 * *")
        with pytest.raises(ValueError):
            Cron("61 9 * * *")

    def test_in_window(self):
        # 09:00 UTC daily, one hour long
        assert in_window("0 9 * * *", 3600, ts(2026, 7, 29, 9, 30))
        assert in_window("0 9 * * *", 3600, ts(2026, 7, 29, 9, 0))
        assert not in_window("0 9 * * *", 3600, ts(2026, 7, 29, 10, 0))
        assert not in_window("0 9 * * *", 3600, ts(2026, 7, 29, 8, 59))


def mkpool_budgets(budgets):
    return NodePool(
        meta=ObjectMeta(name="default"),
        template=NodeClaimTemplate(),
        disruption=Disruption(
            consolidation_policy="WhenEmptyOrUnderutilized",
            consolidate_after_s=0.0,
            budgets=budgets,
        ),
    )


def mkpod(name, cpu="200m", mem="256Mi", labels=None, annotations=None, **kw):
    return Pod(
        meta=ObjectMeta(name=name, uid=name, labels=labels or {},
                        annotations=annotations or {}),
        requests=Resources.parse({"cpu": cpu, "memory": mem}),
        **kw,
    )


class FakeWallClock:
    def __init__(self, epoch):
        self.t = epoch

    def __call__(self):
        return self.t


def two_node_setup(op, budgets=None, annotations=(None, None)):
    op.store.create(st.NODEPOOLS, mkpool_budgets(budgets or [Budget()]))
    tsc = TopologySpreadConstraint(
        max_skew=1, topology_key=wk.HOSTNAME_LABEL, label_selector={"app": "w"}
    )
    for i in range(2):
        op.store.create(
            st.PODS,
            mkpod(f"w{i}", labels={"app": "w"}, topology_spread=[tsc],
                  annotations=annotations[i] or {}),
        )
    op.manager.settle()
    assert len(op.store.list(st.NODES)) == 2
    for i in range(2):
        p = op.store.get(st.PODS, f"w{i}")
        p.topology_spread = []
        op.store.update(st.PODS, p)
    op.clock.advance(30)


class TestBudgetWindows:
    def _op(self):
        clock = FakeClock()
        o = new_kwok_operator(clock=clock)
        o.clock = clock
        return o

    def _dc(self, op) -> DisruptionController:
        return next(
            c for c in op.manager.controllers if isinstance(c, DisruptionController)
        )

    def test_zero_budget_inside_window_blocks(self):
        op = self._op()
        freeze = [Budget(nodes="0", schedule="0 9 * * *", duration_s=3600.0)]
        two_node_setup(op, budgets=freeze)
        dc = self._dc(op)
        dc.wall_clock = FakeWallClock(ts(2026, 7, 29, 9, 30))
        op.manager.settle()
        assert len(op.store.list(st.NODES)) == 2, "frozen window must block"

        # window over: the budget no longer constrains; default 10%->ceil
        # still allows one node per loop and consolidation proceeds
        dc.wall_clock = FakeWallClock(ts(2026, 7, 29, 11, 0))
        op.manager.settle()
        assert len(op.store.list(st.NODES)) < 2

    def test_schedule_without_duration_rejected_at_admission(self):
        # the CRD rule ("'schedule' must be set with 'duration'",
        # karpenter.sh_nodepools.yaml:140) now runs as store admission; the
        # controller's never-constrains defense stays for objects that
        # bypass admission (e.g. restored from an old snapshot)
        from karpenter_tpu.api.validation import ValidationError

        op = self._op()
        broken = [Budget(nodes="0", schedule="0 9 * * *", duration_s=None)]
        with pytest.raises(ValidationError):
            op.store.create(st.NODEPOOLS, mkpool_budgets(broken))
        dc = self._dc(op)
        assert dc._budget_active(Budget(nodes="0", schedule="0 9 * * *", duration_s=None)) is False


class TestRanking:
    def _op(self):
        clock = FakeClock()
        o = new_kwok_operator(clock=clock)
        o.clock = clock
        return o

    def _dc(self, op) -> DisruptionController:
        return next(
            c for c in op.manager.controllers if isinstance(c, DisruptionController)
        )

    def test_deletion_cost_orders_candidates(self):
        op = self._op()
        two_node_setup(
            op,
            annotations=({wk.POD_DELETION_COST_ANNOTATION: "5000"}, None),
        )
        cands = self._dc(op)._candidates()
        assert len(cands) == 2
        # w1's node (no deletion cost) must rank first (cheapest to disrupt)
        assert [p.meta.name for p in cands[0].pods] == ["w1"]
        assert cands[0].cost < cands[1].cost

    def test_negative_deletion_cost_prefers_node(self):
        op = self._op()
        two_node_setup(
            op,
            annotations=({wk.POD_DELETION_COST_ANNOTATION: "-900"}, None),
        )
        cands = self._dc(op)._candidates()
        assert [p.meta.name for p in cands[0].pods] == ["w0"]

    def test_lifetime_remaining_scales_cost(self):
        op = self._op()
        two_node_setup(op)
        dc = self._dc(op)
        # age one claim close to its expiry: it becomes nearly free to disrupt
        claims = sorted(op.store.list(st.NODECLAIMS), key=lambda c: c.meta.name)
        claims[1].expire_after_s = 100.0
        claims[1].meta.creation_timestamp = op.clock() - 90.0
        op.store.update(st.NODECLAIMS, claims[1])
        cands = dc._candidates()
        assert cands[0].claim.name == claims[1].name
        assert cands[0].cost < cands[1].cost
