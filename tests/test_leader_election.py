"""Leader election / HA: two control-plane instances, one active.

The reference's singleton-HA model (lease-based leader election,
settings.md:21; DISABLE_LEADER_ELECTION Makefile:56): standbys run no
controllers until the leader's lease expires, then take over and continue
the control loop where it left off.
"""

import pytest

from karpenter_tpu.controllers import store as st
from karpenter_tpu.controllers.leaderelection import (
    LEADER_LEASE_NAME,
    LEASES,
    LeaderElector,
)
from karpenter_tpu.operator.operator import new_kwok_operator

from tests.test_e2e_kwok import FakeClock, mkpod, mkpool


class TestElector:
    def test_first_candidate_wins(self):
        store = st.Store()
        clock = FakeClock()
        a = LeaderElector(store, "a", clock=clock)
        b = LeaderElector(store, "b", clock=clock)
        a.tick()
        b.tick()
        assert a.is_leader() and not b.is_leader()

    def test_takeover_on_expiry(self):
        store = st.Store()
        clock = FakeClock()
        a = LeaderElector(store, "a", lease_s=15, clock=clock)
        b = LeaderElector(store, "b", lease_s=15, clock=clock)
        a.tick()
        b.tick()
        clock.advance(16)  # leader stops renewing (crashed)
        b.tick()
        assert b.is_leader()
        a.tick()  # the zombie observes it lost
        assert not a.is_leader()

    def test_renewal_keeps_leadership(self):
        store = st.Store()
        clock = FakeClock()
        a = LeaderElector(store, "a", lease_s=15, renew_s=10, clock=clock)
        b = LeaderElector(store, "b", lease_s=15, clock=clock)
        a.tick()
        for _ in range(5):
            clock.advance(6)
            a.tick()
            b.tick()
            assert a.is_leader() and not b.is_leader()

    def test_resign_hands_off_immediately(self):
        store = st.Store()
        clock = FakeClock()
        a = LeaderElector(store, "a", clock=clock)
        b = LeaderElector(store, "b", clock=clock)
        a.tick()
        a.resign()
        b.tick()
        assert b.is_leader() and not a.is_leader()


class TestStandbyHandoff:
    def test_standby_takes_over_the_control_loop(self):
        """Two operators share the store+cloud; the leader provisions, dies,
        and the standby finishes the next wave (VERDICT r3 missing #9)."""
        clock = FakeClock()
        leader = new_kwok_operator(
            clock=clock, leader_elect=True, identity="leader"
        )
        leader.clock = clock
        standby = new_kwok_operator(
            clock=clock,
            leader_elect=True,
            identity="standby",
            shared_store=leader.store,
            shared_cloud=leader.cloud,
        )
        standby.clock = clock

        leader.store.create(st.NODEPOOLS, mkpool())
        leader.store.create(st.PODS, mkpod("p0", cpu="500m"))
        leader.manager.settle()
        assert leader.store.get(st.PODS, "p0").node_name is not None

        # the standby is inert while the leader renews
        standby.manager.tick()
        assert not standby.manager.elector.is_leader()

        # leader dies (stops renewing); a second wave arrives
        leader.store.create(st.PODS, mkpod("p1", cpu="500m"))
        clock.advance(20)  # past the lease
        standby.manager.settle()
        assert standby.manager.elector.is_leader()
        assert standby.store.get(st.PODS, "p1").node_name is not None
        lease = standby.store.get(LEASES, LEADER_LEASE_NAME)
        assert lease.holder == "standby"


class TestRestartAndResign:
    def test_restarted_leader_reclaims_own_lease(self):
        """A leader that crashes and comes back with the SAME identity renews
        its unexpired lease immediately (kube renews on identity match) —
        no dead window of up to lease_s with zero active controllers."""
        store = st.Store()
        clock = FakeClock()
        a = LeaderElector(store, "a", lease_s=15, clock=clock)
        a.tick()
        assert a.is_leader()
        clock.advance(1)  # well within the lease
        a2 = LeaderElector(store, "a", lease_s=15, clock=clock)  # restart
        a2.tick()
        assert a2.is_leader(), "identity match must reclaim without waiting"
        # and the reclaim was a real CAS renewal, not just a local flag
        assert store.get(LEASES, LEADER_LEASE_NAME).renew_time == clock()

    def test_resign_clears_holder(self):
        """resign() empties the holder: the resigner does not auto-reclaim on
        its next tick; another candidate takes the expired lease at once."""
        store = st.Store()
        clock = FakeClock()
        a = LeaderElector(store, "a", clock=clock)
        b = LeaderElector(store, "b", clock=clock)
        a.tick()
        a.resign()
        assert store.get(LEASES, LEADER_LEASE_NAME).holder == ""
        b.tick()
        assert b.is_leader()
        a.tick()
        assert not a.is_leader()


def test_manager_stop_resigns_for_fast_handoff():
    """Clean shutdown must hand off immediately (kube ReleaseOnCancel):
    Manager.stop resigns the lease so the standby acquires on its NEXT tick
    instead of waiting out the lease duration."""
    from karpenter_tpu.controllers.manager import Manager

    store = st.Store()
    clock = FakeClock()
    a = LeaderElector(store, "a", lease_s=15, clock=clock)
    b = LeaderElector(store, "b", lease_s=15, clock=clock)
    ma = Manager(elector=a)
    ma.tick()
    assert a.is_leader()
    ma.stop()  # clean shutdown
    clock.advance(0.1)  # far inside what WOULD have been the lease window
    b.tick()
    assert b.is_leader(), "standby must take over without waiting for expiry"


class TestClockSkew:
    def test_skewed_candidate_cannot_seize_live_lease(self):
        """Cross-host skew regression: a candidate whose clock runs far
        AHEAD of the holder's must not judge expiry from the holder's
        wall-clock renew_time (the old `now - renew_time` check made it
        seize instantly — dual leaders). Client-go semantics: expiry is
        measured on the OBSERVER's clock from the moment it last saw the
        record change."""
        store = st.Store()
        ca, cb = FakeClock(), FakeClock()
        cb.advance(3600)  # candidate's clock is an hour ahead of the holder's
        a = LeaderElector(store, "a", lease_s=15, renew_s=10, clock=ca)
        b = LeaderElector(store, "b", lease_s=15, clock=cb)
        a.tick()
        b.tick()
        assert a.is_leader() and not b.is_leader(), (
            "skewed candidate seized a fresh lease"
        )
        for _ in range(5):
            ca.advance(6)
            cb.advance(6)
            a.tick()
            b.tick()
            assert a.is_leader() and not b.is_leader(), (
                "skewed candidate seized a LIVE, renewing lease"
            )
        # the holder dies: expiry runs on b's own clock from its last
        # observed record change, so takeover still works
        cb.advance(16)
        b.tick()
        assert b.is_leader()

    def test_skewed_behind_candidate_still_takes_over_expiry(self):
        """Skew the other way: a candidate BEHIND the holder's clock sees
        renew_time in its future; the old check would never fire (lease
        immortal). Observation-based expiry is skew-independent."""
        store = st.Store()
        ca, cb = FakeClock(), FakeClock()
        ca.advance(3600)  # holder's clock is an hour ahead
        a = LeaderElector(store, "a", lease_s=15, clock=ca)
        b = LeaderElector(store, "b", lease_s=15, clock=cb)
        a.tick()
        b.tick()
        assert a.is_leader() and not b.is_leader()
        cb.advance(16)  # holder silent for a full lease on b's clock
        b.tick()
        assert b.is_leader(), "lease became immortal under backward skew"
