#!/usr/bin/env python3
"""Explain-record diff between two solver backends on the SAME input.

The explain subsystem (karpenter_tpu/obs/explain.py) derives a canonical
per-solve record — per-pod chosen placement, per-group rejection table,
preemptions — whose fingerprint is a stable content hash. This CLI solves
one scenario with two backends (default: the FFD kernel vs the convex
ADMM backend), builds both records on the host, and reports where the
decisions diverge:

    python tools/explain_diff.py --scenario rightsize
    python tools/explain_diff.py --scenario uniform --json

Output: both fingerprints, a per-pod decision table (chosen column per
backend, agreement mark), and the first-divergence paths from
explain.diff_records. Divergence is NOT failure — the convex backend is
ALLOWED to pick cheaper shapes than FFD (that is its point); the table is
how a human audits that the disagreement is an improvement, not a
scattering. The quality suite (bench.py --quality-suite) embeds
`diff_solves` output so every bench record carries the audit trail.

Exit status: 0 always for successful runs (divergence is data, not an
error), 2 on usage errors. Needs the repo importable (run from the repo
root or with PYTHONPATH=.).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


# -- self-contained scenario fixtures -----------------------------------------


_ZONES = ("zone-1a", "zone-1b")


def _mktype(name: str, cpu: int, mem_gib: int, price: float):
    from karpenter_tpu.api import wellknown as wk
    from karpenter_tpu.cloudprovider.types import InstanceType, Offering
    from karpenter_tpu.scheduling.requirements import IN, Requirement, Requirements
    from karpenter_tpu.utils.resources import Resources

    reqs = Requirements.of(
        Requirement.create(wk.INSTANCE_TYPE_LABEL, IN, [name]),
        Requirement.create(wk.ARCH_LABEL, IN, ["amd64"]),
        Requirement.create(wk.OS_LABEL, IN, ["linux"]),
        Requirement.create(wk.ZONE_LABEL, IN, list(_ZONES)),
        Requirement.create(wk.CAPACITY_TYPE_LABEL, IN, ["on-demand"]),
    )
    cap = Resources.parse({"cpu": str(cpu), "memory": f"{mem_gib}Gi"})
    cap["pods"] = 110
    return InstanceType(
        name=name, requirements=reqs, capacity=cap, overhead=Resources(),
        offerings=[Offering(zone=z, capacity_type="on-demand", price=price)
                   for z in _ZONES],
    )


def _pool(name: str, weight: int, types: list):
    from karpenter_tpu.api import wellknown as wk
    from karpenter_tpu.provisioning.scheduler import NodePoolSpec
    from karpenter_tpu.scheduling.requirements import IN, Requirement, Requirements
    from karpenter_tpu.utils.resources import Resources

    r = Requirements.of(Requirement.create(wk.NODEPOOL_LABEL, IN, [name]))
    return NodePoolSpec(name=name, weight=weight, requirements=r, taints=[],
                        instance_types=types, limits=Resources())


def _mkpod(name: str, cpu: str, mem: str):
    from karpenter_tpu.api.objects import ObjectMeta, Pod
    from karpenter_tpu.utils.resources import Resources

    return Pod(meta=ObjectMeta(name=name, uid=name),
               requests=Resources.parse({"cpu": cpu, "memory": mem}))


def _mknode(name: str, cpu: str, mem: str, zone: str = "zone-1a"):
    from karpenter_tpu.api import wellknown as wk
    from karpenter_tpu.provisioning.scheduler import ExistingNode
    from karpenter_tpu.utils.resources import Resources

    lab = {wk.ZONE_LABEL: zone, wk.HOSTNAME_LABEL: name,
           wk.CAPACITY_TYPE_LABEL: "on-demand", wk.ARCH_LABEL: "amd64",
           wk.OS_LABEL: "linux"}
    free = Resources.parse({"cpu": cpu, "memory": mem})
    free["pods"] = 110
    return ExistingNode(id=name, labels=lab, taints=[], free=free)


def build_scenario(name: str):
    """Three canned shapes spanning the interesting decision space:

    uniform   one pool, one 4-cpu shape, 12 x 1cpu pods — a known optimum
              both backends must hit (3 claims), so the records should be
              equivalent modulo claim numbering.
    rightsize two pools in weight-vs-price contention: FFD follows pool
              weight onto 4-cpu $1.00 nodes, the convex objective follows
              price onto 16-cpu $0.90 nodes — maximal legitimate
              divergence, the quality suite's savings config.
    split     two half-full existing 8-cpu nodes plus 8 x 3cpu pods: both
              backends must fill the sunk existing capacity first.
    """
    from karpenter_tpu.provisioning.scheduler import SolverInput

    if name == "uniform":
        pods = [_mkpod(f"u{i:02d}", "1", "1Gi") for i in range(12)]
        pools = [_pool("general", 0, [_mktype("std.xlarge", 4, 16, 1.0)])]
        return SolverInput(pods=pods, nodes=[], nodepools=pools,
                           zones=_ZONES, capacity_types=("on-demand",))
    if name == "rightsize":
        pods = [_mkpod(f"w{i:03d}", "1", "1Gi") for i in range(96)]
        pools = [
            _pool("boutique", 100, [_mktype("boutique.xlarge", 4, 16, 1.0)]),
            _pool("warehouse", 0, [_mktype("warehouse.4xlarge", 16, 64, 0.9)]),
        ]
        return SolverInput(pods=pods, nodes=[], nodepools=pools,
                           zones=_ZONES, capacity_types=("on-demand",))
    if name == "split":
        pods = [_mkpod(f"q{i:02d}", "3", "4Gi") for i in range(8)]
        nodes = [_mknode("n1", "8", "32Gi"),
                 _mknode("n2", "8", "32Gi", zone="zone-1b")]
        pools = [_pool("general", 0, [_mktype("std.4xlarge", 16, 64, 0.9)])]
        return SolverInput(pods=pods, nodes=nodes, nodepools=pools,
                           zones=_ZONES, capacity_types=("on-demand",))
    raise ValueError(f"unknown scenario {name!r}")


# -- the diff core (imported by bench.py --quality-suite) ----------------------


def diff_solves(inp, solver_a, solver_b, label_a: str = "ffd",
                label_b: str = "convex") -> dict:
    """Solve `inp` with both backends, build the canonical explain record
    for each on the host, and return the structured diff: fingerprints,
    per-pod decision table, agreement count, and first-divergence paths.
    Claim columns compare by (kind, index-within-backend) — claim numbering
    is solver-order deterministic per backend, not comparable across
    backends, so the table shows both and `agree` means literal equality.
    """
    from karpenter_tpu.obs import explain as obsexplain
    from karpenter_tpu.solver.encode import encode, quantize_input

    enc = encode(quantize_input(inp))
    res_a = solver_a.solve(inp)
    res_b = solver_b.solve(inp)
    rec_a = obsexplain.build_record(enc, res_a)
    rec_b = obsexplain.build_record(enc, res_b)
    table: List[dict] = []
    agree = 0
    for uid in sorted(rec_a["pods"]):
        ca = rec_a["pods"][uid]["chosen"]
        cb = rec_b["pods"].get(uid, {}).get("chosen")
        same = ca == cb
        agree += int(same)
        table.append({"pod": uid, label_a: ca, label_b: cb, "agree": same})
    return {
        "scenario_pods": len(table),
        "pods_agree": agree,
        "fingerprint_" + label_a: obsexplain.fingerprint(rec_a),
        "fingerprint_" + label_b: obsexplain.fingerprint(rec_b),
        "identical": obsexplain.fingerprint(rec_a) == obsexplain.fingerprint(rec_b),
        "claims_" + label_a: len(res_a.claims),
        "claims_" + label_b: len(res_b.claims),
        "errors_" + label_a: len(res_a.errors),
        "errors_" + label_b: len(res_b.errors),
        "divergences": obsexplain.diff_records(rec_a, rec_b),
        "table": table,
    }


def _fmt_chosen(c) -> str:
    if c is None:
        return "UNPLACED"
    kind, ref = c
    return f"{kind}:{ref}"


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="explain_diff",
        description="diff per-pod explain records between two backends")
    ap.add_argument("--scenario", default="rightsize",
                    choices=("uniform", "rightsize", "split"))
    ap.add_argument("--backend-a", default="ffd", choices=("ffd", "reference"),
                    help="baseline backend (default: ffd kernel)")
    ap.add_argument("--convex-max-iters", type=int, default=400)
    ap.add_argument("--json", action="store_true",
                    help="emit the full structured diff as one JSON object")
    args = ap.parse_args(argv)
    if args.convex_max_iters < 1:
        print("explain_diff: --convex-max-iters must be >= 1", file=sys.stderr)
        return 2

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from karpenter_tpu.solver.backend import ReferenceSolver, TPUSolver
    from karpenter_tpu.solver.convex import ConvexSolver

    inp = build_scenario(args.scenario)
    solver_a = TPUSolver() if args.backend_a == "ffd" else ReferenceSolver()
    solver_b = ConvexSolver(TPUSolver(), max_iters=args.convex_max_iters)
    out = diff_solves(inp, solver_a, solver_b, label_a=args.backend_a)
    out["scenario"] = args.scenario
    if args.json:
        print(json.dumps(out, indent=2))
        return 0

    print(f"explain_diff: scenario={args.scenario} "
          f"{args.backend_a} vs convex")
    print(f"  fingerprints: {out['fingerprint_' + args.backend_a][:16]} vs "
          f"{out['fingerprint_convex'][:16]}"
          + ("  (identical)" if out["identical"] else ""))
    print(f"  claims: {out['claims_' + args.backend_a]} vs "
          f"{out['claims_convex']}   pods agreeing: "
          f"{out['pods_agree']}/{out['scenario_pods']}")
    width = max((len(r["pod"]) for r in out["table"]), default=3)
    for r in out["table"]:
        mark = " " if r["agree"] else "*"
        print(f"  {mark} {r['pod']:<{width}}  "
              f"{_fmt_chosen(r[args.backend_a]):<16} "
              f"{_fmt_chosen(r['convex'])}")
    if out["divergences"]:
        print("  first-divergence paths:")
        for d in out["divergences"][:12]:
            print(f"    {d}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
