#!/usr/bin/env python3
"""Bench regression gate: diff two BENCH_rNN.json records, exit nonzero
on regression.

The bench harness (bench.py) appends one JSON record per run —
`{"n": NN, "cmd": ..., "rc": ..., "tail": ..., "parsed": {...}}` — whose
`parsed` object carries the headline metrics (kernel/warm/e2e p50/p99,
upload/decode bytes, arrival_batches_per_sec, ...). This CLI is the
first CI-able perf guardrail over them:

    python tools/bench_gate.py --baseline BENCH_r03.json
    python tools/bench_gate.py --baseline BENCH_r03.json --current run.json

Rules (solver/SPEC.md "Telemetry semantics"):

- only keys NUMERIC AND > 0 on BOTH sides compare — marker records
  (`value: -1`, `backend_unavailable: true`, `parsed: null`) and keys
  one side lacks are skipped with a note, never failed. A record from a
  host without the accelerator toolchain therefore always gates clean.
- direction is per key: names containing per_sec / rate / hit /
  speedup / shrink / coverage are higher-is-better; everything else
  (latencies, bytes, counts) is lower-is-better.
- tolerance is per key (`TOLERANCES`, else a p99/first-call heuristic,
  else `--default-tolerance`): regression means the current value is
  outside baseline * (1 +/- tolerance) in the bad direction.

Exit status: 0 = no regression (including "nothing comparable", which
prints a warning — an empty gate must not masquerade as a green one
silently), 1 = at least one regression, 2 = usage/IO error. Stdlib only.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

# metadata / bookkeeping keys that are never performance metrics
SKIP_KEYS = {
    "n", "rc", "vs_baseline", "backend_unavailable", "wall_time",
    "unit", "metric", "reason", "cmd", "tail",
}

# per-key relative tolerances; anything absent falls through the
# heuristic in tolerance_for(). Tail latencies get more slack than
# medians; byte counters are near-deterministic and get less.
TOLERANCES: Dict[str, float] = {
    "solve_p99_50k_pods_x_700_types": 0.25,
    "kernel_pipelined_ms": 0.20,
    "link_roundtrip_ms": 0.25,
    "e2e_p50_ms": 0.20,
    "e2e_p99_ms": 0.30,
    "config3_e2e_p50_ms": 0.25,
    "config4_e2e_p50_ms": 0.25,
    "upload_bytes_per_solve": 0.10,
    "decode_bytes_per_solve": 0.10,
    "arrival_batches_per_sec": 0.20,
    # aggregate tenant throughput (ISSUE 16 cohort fusion): host-seam
    # scheduling throughput is contention-noisy, give it tail-class slack
    "aggregate_solves_per_sec": 0.30,
    "tenant_aggregate_solves_per_sec": 0.30,
    # durable resident state (ISSUE 17): restart paths are single-shot
    # wall-clock (no percentile smoothing), so tail-class slack; both are
    # lower-is-better — the cold leg regressing means the encode rebuild
    # itself regressed, the vault leg regressing means restore overhead
    # is eating the donor-adopt win
    "restart_to_first_solve_ms": 0.30,
    "restart_to_first_solve_cold_ms": 0.30,
    "vault_snapshot_ms": 0.35,
    "handover_wall_ms": 0.35,
    # federation (ISSUE 18): subprocess-host throughput is scheduler-noisy
    # on shared runners (tail-class slack, higher-is-better via pattern /
    # explicit keys below); failover recovery is single-shot wall-clock of
    # a queue drain — lower-is-better, tail-class slack.
    # federation_dropped_solves is asserted == 0 inside the suite (the
    # gate skips <= 0 keys by design, so the suite itself is the gate).
    "federated_solves_per_sec": 0.30,
    "federated_solves_per_sec_1h": 0.30,
    "scaling_efficiency_4h": 0.15,
    "failover_recovery_ms": 0.35,
    # solver quality suite (ISSUE 19 convex backend): node counts are
    # deterministic integers — any increase is a real packing regression,
    # zero slack. Savings tracks the node counts (ratio of two integers,
    # small slack for config drift); solve wall-clock is host-noisy.
    "nodes_provisioned_ffd": 0.0,
    "nodes_provisioned_convex": 0.0,
    "consolidation_savings_pct": 0.10,
    "convex_solve_ms": 0.35,
    "admm_iterations_to_converge": 0.25,
    # sparse constraint engine (ISSUE 20): constrained-config medians are
    # host-noisy like the other e2e p50s; the ratios vs the unconstrained
    # base are what the acceptance targets (<= 2x / 1.7x) actually bound,
    # so they get tighter slack. constraint_density is deterministic for a
    # fixed fleet shape — any drift means the builder or encoder changed.
    "constrained_solve_p50_ms_config3": 0.25,
    "constrained_solve_p50_ms_config4": 0.25,
    "constrained_vs_base_ratio_config3": 0.15,
    "constrained_vs_base_ratio_config4": 0.15,
    "constraint_density": 0.0,
    # axis-eval compaction: higher-is-better (pinned below); the dense leg
    # is memory-bound and runner-sensitive, tail-class slack
    "sparse_speedup_x": 0.35,
    # parity proof: 1 or the suite itself already failed — zero slack
    "sharded_constrained_ok": 0.0,
}

HIGHER_BETTER_PAT = re.compile(
    r"per_sec|_rate|rate_|hit|speedup|shrink|coverage")

# explicit higher-is-better keys: direction must not depend on the name
# pattern surviving a rename (the cohort-fusion acceptance gates on this)
HIGHER_BETTER_KEYS = {
    "aggregate_solves_per_sec",
    "tenant_aggregate_solves_per_sec",
    "cohort_size_mean",
    # no "per_sec"/"speedup" token in the name — pin the direction
    "scaling_efficiency_4h",
    # convex-vs-FFD consolidation win: bigger savings = better packing
    # ("savings" matches no direction pattern — pin it)
    "consolidation_savings_pct",
    # sparse axis compaction (ISSUE 20): the name pattern already matches
    # "speedup", but the acceptance gates on this key — pin it against a
    # rename breaking the direction
    "sparse_speedup_x",
    # mesh-sharded constrained parity: 1 = served + bit-identical; a drop
    # to 0 is a regression even though it's not a latency
    "sharded_constrained_ok",
}


def tolerance_for(key: str, default: float) -> float:
    if key in TOLERANCES:
        return TOLERANCES[key]
    if "p99" in key:
        return 0.30
    if "first_call" in key:  # cold-start compile time: wildly host-dependent
        return 1.00
    return default


def higher_is_better(key: str) -> bool:
    return key in HIGHER_BETTER_KEYS or bool(HIGHER_BETTER_PAT.search(key))


def extract_metrics(record: object, prefix: str = "") -> Dict[str, float]:
    """Flatten a bench record to {metric_name: value}. Understands the
    `{"metric": name, "value": v}` convention (the pair collapses to one
    entry under `name`) and recurses through `parsed`/nested dicts."""
    out: Dict[str, float] = {}
    if not isinstance(record, dict):
        return out
    named = record.get("metric")
    if isinstance(named, str) and isinstance(
            record.get("value"), (int, float)):
        out[named] = float(record["value"])
    for key, val in record.items():
        if key in SKIP_KEYS or key == "value":
            continue
        if isinstance(val, bool):
            continue
        if isinstance(val, (int, float)):
            out[prefix + key] = float(val)
        elif isinstance(val, dict):
            out.update(extract_metrics(
                val, prefix="" if key == "parsed" else prefix + key + "."))
    return out


def newest_bench_record(root: str) -> Optional[str]:
    """Highest-numbered BENCH_rNN.json under `root` (the repo convention:
    the newest run has the highest NN)."""
    paths = glob.glob(os.path.join(root, "BENCH_r*.json"))

    def num(p: str) -> int:
        m = re.search(r"BENCH_r(\d+)\.json$", p)
        return int(m.group(1)) if m else -1

    paths = [p for p in paths if num(p) >= 0]
    return max(paths, key=num) if paths else None


def compare(baseline: Dict[str, float], current: Dict[str, float],
            default_tolerance: float) -> Tuple[List[dict], List[str]]:
    """(rows, skipped): one row per comparable key, names of skipped ones."""
    rows: List[dict] = []
    skipped: List[str] = []
    for key in sorted(set(baseline) | set(current)):
        base = baseline.get(key)
        cur = current.get(key)
        if (base is None or cur is None or base <= 0 or cur <= 0):
            skipped.append(key)
            continue
        tol = tolerance_for(key, default_tolerance)
        hib = higher_is_better(key)
        if hib:
            limit = base * (1.0 - tol)
            regressed = cur < limit
        else:
            limit = base * (1.0 + tol)
            regressed = cur > limit
        rows.append({
            "key": key, "baseline": base, "current": cur,
            "delta_pct": (cur - base) / base * 100.0,
            "tolerance_pct": tol * 100.0,
            "direction": "higher_better" if hib else "lower_better",
            "regressed": regressed,
        })
    return rows, skipped


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="bench_gate",
        description="diff BENCH_rNN.json metrics; exit 1 on regression")
    ap.add_argument("--baseline", required=True,
                    help="baseline BENCH_rNN.json (the reference run)")
    ap.add_argument("--current", default=None,
                    help="run under test (default: newest BENCH_r*.json "
                         "next to the baseline)")
    ap.add_argument("--default-tolerance", type=float, default=0.20,
                    help="relative tolerance for keys without a per-key "
                         "entry (default 0.20)")
    args = ap.parse_args(argv)
    if args.default_tolerance < 0:
        print("bench_gate: --default-tolerance must be >= 0", file=sys.stderr)
        return 2
    current_path = args.current
    if current_path is None:
        current_path = newest_bench_record(
            os.path.dirname(os.path.abspath(args.baseline)))
        if current_path is None:
            print("bench_gate: no BENCH_r*.json found for --current",
                  file=sys.stderr)
            return 2
    try:
        with open(args.baseline) as f:
            base_rec = json.load(f)
        with open(current_path) as f:
            cur_rec = json.load(f)
    except (OSError, ValueError) as e:
        print(f"bench_gate: {e}", file=sys.stderr)
        return 2
    rows, skipped = compare(
        extract_metrics(base_rec), extract_metrics(cur_rec),
        args.default_tolerance)
    print(f"bench_gate: {os.path.basename(args.baseline)} -> "
          f"{os.path.basename(current_path)}")
    for r in rows:
        mark = "REGRESSED" if r["regressed"] else "ok"
        arrow = "^" if r["direction"] == "higher_better" else "v"
        print(f"  [{mark:>9}] {r['key']:<36} {r['baseline']:>12.2f} -> "
              f"{r['current']:>12.2f}  ({r['delta_pct']:+.1f}%, "
              f"tol {r['tolerance_pct']:.0f}% {arrow})")
    if skipped:
        print(f"  skipped (missing/non-positive on a side): "
              f"{', '.join(skipped)}")
    bad = [r for r in rows if r["regressed"]]
    if bad:
        print(f"bench_gate: {len(bad)} regression(s)", file=sys.stderr)
        return 1
    if not rows:
        # marker-only records (e.g. backend_unavailable) gate clean, loudly
        print("bench_gate: WARNING — no comparable metrics; gate is vacuous")
    return 0


if __name__ == "__main__":
    sys.exit(main())
